"""Persistent device loop for compiled stage programs.

The staged executor dispatches one XLA program per batch (the fused
chain step) plus a host sync for the overflow scalar — at BENCH_SF100's
~100ms dispatch RTT the engine is dispatch-bound, not compute-bound.
This loop folds a CHUNK of bucket-padded batches per dispatch:
`lax.fori_loop` runs chain + probe-insert + accumulate for every batch
of the chunk inside ONE program, carrying the agg hash table across
iterations with buffer donation, so Python-side dispatches per
partition drop from O(batches x operators) to O(chunks).

Discipline inherited from the staged path, kept intact:

  * ATOMIC overflow (hash_agg_step): the first batch that overflows
    leaves the carry unchanged and masks every later batch of the chunk
    to a no-op; the host doubles + rehashes (exact modes) and resumes
    the SAME chunk at the overflow batch — bit-identical to the staged
    grow schedule.  Partial mode keeps its skip semantics by falling
    back wholesale instead of growing (the loop emits nothing until its
    final drain, so the staged re-run is lossless).
  * Cancellation/deadline (PR 7): the query token is checked between
    chunks (and per source batch by the metered stream), so teardown
    latency is bounded by one chunk.
  * Fault injection (PR 4): the `device-loop` site fires at chunk
    boundaries; an injected fault becomes a wholesale fallback, never a
    divergent result.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

import jax
import jax.numpy as jnp
import numpy as np

from blaze_tpu import config, faults
from blaze_tpu.bridge import tracing, xla_stats
from blaze_tpu.bridge.context import current_task
from blaze_tpu.bridge.xla_stats import meter_jit
from blaze_tpu.parallel.stage import hash_agg_step, init_hash_carry

# hard ceiling on grow-on-overflow table size: past this the partition
# is cheaper to re-run staged (which streams) than to hold on device
_MAX_SLOTS = 1 << 24


class StageLoopFallback(RuntimeError):
    """The loop declined or failed BEFORE emitting anything; the caller
    re-runs the partition through the staged per-batch executor.  Like
    DeviceExchangeError, this is an optimization bailing out — never a
    new failure mode."""


# fingerprint -> jit'd chunk fold; bounded FIFO like fused's step caches
_FOLD_CACHE: dict = {}
_FOLD_LIMIT = 128

# -- regrow fences (overlapped exchange) ------------------------------------
# The overlapped exchange (plan/stages.py) keeps previous chunks'
# all-to-all collectives in flight while this loop folds the next chunk.
# A hash-table regrow is the one point where that is unsafe: the rehash
# doubles the live table while in-flight tickets still pin their
# send/receive buffers, and the overflow/rehash contract is atomic —
# so the overlap scheduler registers a fence that drains every in-flight
# ticket, and the loop runs all fences RIGHT BEFORE each regrow.

_FENCE_LOCK = threading.Lock()
_FENCES: list = []


@contextmanager
def exchange_fence(fn):
    """Register `fn` to run before every hash-table regrow for the
    duration of the `with` body.  Fences are global (not per-query):
    an extra drain of another query's tickets only adds waiting, never
    changes results."""
    with _FENCE_LOCK:
        _FENCES.append(fn)
    try:
        yield
    finally:
        with _FENCE_LOCK:
            _FENCES.remove(fn)


def _run_fences() -> None:
    with _FENCE_LOCK:
        fences = list(_FENCES)
    for fn in fences:
        fn()


def _fold_factory(program, donate: bool, lane: str = "scatter"):
    skey = (program.fingerprint, bool(donate), lane)
    fold = _FOLD_CACHE.get(skey)
    if fold is not None:
        return fold
    if len(_FOLD_CACHE) >= _FOLD_LIMIT:
        _FOLD_CACHE.pop(next(iter(_FOLD_CACHE)))
    prepare = program.prepare
    kinds = program.kinds

    def fold_impl(carry, cols_stacked, masks, start):
        def body(b, state):
            c, ovf_seen, first_ovf = state
            cols_b = tuple(
                None if col is None else (col[0][b], col[1][b])
                for col in cols_stacked)
            kd, kv, ad, av, m = prepare(cols_b, masks[b])
            # once a batch overflows, later batches fold as no-ops: the
            # carry stays exactly at the pre-overflow state (hash_agg_step
            # is atomic), so the host can regrow and resume mid-chunk
            live = jnp.logical_and(m, jnp.logical_not(ovf_seen))
            specs = [(k, d, v) for k, d, v in zip(kinds, ad, av)]
            new_c, ovf, _ng = hash_agg_step(c, list(zip(kd, kv)), specs,
                                            live, lane=lane)
            hit = ovf > 0
            first_ovf = jnp.where(hit & ~ovf_seen,
                                  jnp.asarray(b, jnp.int32), first_ovf)
            return (new_c, jnp.logical_or(ovf_seen, hit), first_ovf)

        init = (carry, jnp.asarray(False), jnp.asarray(0, jnp.int32))
        return jax.lax.fori_loop(start, masks.shape[0], body, init)

    kwargs = {"donate_argnums": (0,)} if donate else {}
    fold = meter_jit(fold_impl, name="runtime.stage_loop", **kwargs)
    _FOLD_CACHE[skey] = fold
    return fold


def _donate_active() -> bool:
    """Donation only pays where buffers are device-resident; XLA CPU
    rejects it with a warning per call, so gate on backend."""
    return (config.STAGE_DEVICE_LOOP_DONATE.get()
            and jax.default_backend() != "cpu")


def _pad_chunk(cols_stacked, masks, window: int):
    """Pad a tail chunk up to the full window with masked-out batches so
    every chunk of a rung shares ONE jit signature (the batch-axis analog
    of the row-axis bucket ladder)."""
    w = int(masks.shape[0])
    if w == window:
        return cols_stacked, masks
    extra = window - w

    def padto(a):
        widths = [(0, extra)] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, widths)

    cols = tuple(None if c is None else (padto(c[0]), padto(c[1]))
                 for c in cols_stacked)
    return cols, padto(masks)


def loop_chunk_batches() -> int:
    """Configured chunk width, shrunk for degraded queries: the memory
    degradation ladder (serving/context.py) halves the chunk per shrink
    level — same policy as ops.base.effective_batch_size, floor 1."""
    from blaze_tpu.bridge.context import active_query
    chunk = max(1, config.STAGE_DEVICE_LOOP_CHUNK.get())
    q = active_query()
    if q is not None and q.capacity_shrink:
        chunk = max(1, chunk >> q.capacity_shrink)
    return chunk


def run_partition(program, partition: int, ctx: str = "",
                  source_stream=None):
    """Fold one partition through the stage program; returns the final
    HashAggCarry.  Raises StageLoopFallback on any ineligibility or
    failure — nothing has been emitted at that point, so the caller's
    staged re-run is lossless.  Cancellation (QueryCancelled /
    TaskKilledError / deadline) propagates untranslated."""
    from blaze_tpu.plan.fused import _batch_windows, _pow2, _rehash_jit
    task = current_task()
    q = task.query
    if q is None:
        from blaze_tpu.bridge.context import active_query
        q = active_query()
    if q is not None and q.force_agg_passthrough:
        raise StageLoopFallback("query degraded to agg pass-through")
    chunk = loop_chunk_batches()
    from blaze_tpu.kernels import lane as lane_mod
    lane = lane_mod.resolve("hash")
    fold = _fold_factory(program, _donate_active(), lane)
    slots = _pow2(config.ON_DEVICE_AGG_CAPACITY.get())
    carry = init_hash_carry(list(program.key_dtypes), program.kinds,
                            list(program.acc_dtypes), slots)
    stream = (source_stream if source_stream is not None
              else program.source.execute(partition))
    batches = rows = fold_calls = regrows = ci = 0
    try:
        for cols_stacked, masks, count in _batch_windows(stream, chunk):
            # chunk boundary: the ONLY host sync points of the loop —
            # cooperative cancel, fault site, overflow scalar
            task.check_running()
            faults.maybe_fail("device-loop", stage=ctx, chunk=ci)
            with tracing.span("stage_loop_chunk", stage=ctx,
                              partition=partition, chunk=ci,
                              batches=count):
                rows += int(np.asarray(jnp.sum(masks)))
                cols_stacked, masks = _pad_chunk(cols_stacked, masks,
                                                 chunk)
                start = 0
                while True:
                    carry, ovf_seen, first_ovf = fold(
                        carry, cols_stacked, masks,
                        jnp.asarray(start, jnp.int32))
                    fold_calls += 1
                    if not bool(ovf_seen):
                        break
                    if not program.grow:
                        # PARTIAL mode: skip semantics (batch-local
                        # dedup pass-through) belong to the staged path;
                        # growing here would diverge from its bit
                        # pattern
                        raise StageLoopFallback(
                            "hash table overflow in partial mode")
                    if slots * 2 > _MAX_SLOTS:
                        raise StageLoopFallback(
                            f"table would exceed {_MAX_SLOTS} slots")
                    _run_fences()  # drain in-flight overlapped exchanges
                    slots *= 2
                    bigger, re_ovf, _ = _rehash_jit(program.kinds,
                                                    slots, lane)(carry)
                    if int(re_ovf) > 0:
                        continue  # rare probe clustering: double again
                    carry = bigger
                    regrows += 1
                    start = int(first_ovf)
            ci += 1
            batches += count
            task.loop_chunks = ci
    except faults.InjectedFault as e:
        # scripted chaos at the device-loop site: wholesale fallback,
        # not a task retry — the chaos soak asserts THIS path converges
        raise StageLoopFallback(f"injected fault: {e}") from e
    xla_stats.note_stage_loop_task(
        chunks=fold_calls, batches=batches, rows=rows, regrows=regrows,
        dispatches_avoided=max(0, batches - fold_calls))
    return carry


def _dict_stream_guard(stream, utf8_cols, key_srcs, captured):
    """Wrap a dict-key stage's source stream: every utf8 source column
    must arrive dictionary-encoded (the prepare traced int32 code slots
    for them — a plain utf8 batch, e.g. after encoder overflow, has no
    device form and must fall back BEFORE the fold sees it), and the
    latest dictionary per key source is captured as it passes.  The
    encoder's prefix property makes the LAST dictionary of the stream
    decode every earlier batch's codes, so capture is just
    last-writer-wins."""
    from blaze_tpu.batch import DictColumn
    for batch in stream:
        for ci in utf8_cols:
            c = batch.columns[ci]
            if not isinstance(c, DictColumn) or c.dictionary is None:
                raise StageLoopFallback(
                    "utf8 source column arrived without dictionary "
                    "encoding (encoder overflow or unencoded source)")
            if ci in key_srcs:
                captured[ci] = c.dictionary
        yield batch


def execute_loop(program, partition: int, ctx: str = ""):
    """Generator form for FusedPartialAggExec.execute: fold, then drain
    through the shared emission path (ColumnBatch chunks).  Guaranteed
    to raise StageLoopFallback only BEFORE the first yield."""
    dict_keys = getattr(program, "dict_keys", ())
    if any(s is not None for s in dict_keys):
        from blaze_tpu.schema import TypeId
        utf8_cols = {i for i, f in enumerate(program.source.schema)
                     if f.data_type.id == TypeId.UTF8}
        key_srcs = {s for s in dict_keys if s is not None}
        captured: dict = {}
        stream = _dict_stream_guard(program.source.execute(partition),
                                    utf8_cols, key_srcs, captured)
        carry = run_partition(program, partition, ctx=ctx,
                              source_stream=stream)
        key_dicts = [captured.get(s) if s is not None else None
                     for s in dict_keys]
        yield from program.agg._emit_hash(carry, key_dicts=key_dicts)
        return
    carry = run_partition(program, partition, ctx=ctx)
    yield from program.agg._emit_hash(carry)


def drain_device(program, carry):
    """D2D drain: compact the carry's used slots ON DEVICE and cast to
    the stage out-schema storage dtypes, so the partitioned output feeds
    DeviceExchange without a host round trip.  Returns (datas, valids,
    n) — lists of length-n device arrays in output column order."""
    from blaze_tpu.plan.fused import _bucket
    if any(s is not None for s in getattr(program, "dict_keys", ())):
        # dict-key stages never reach here (utf8 output columns exclude
        # the boundary from DeviceExchange), but raw codes must not leak
        # into an exchange if that ever changes
        raise StageLoopFallback("dict-encoded keys cannot drain D2D")
    used = carry.used
    count = int(jax.device_get(jnp.sum(used)))
    if count == 0:
        return [], [], 0
    padded = _bucket(count, used.shape[0])
    sel = jnp.nonzero(used, size=padded, fill_value=0)[0]
    fields = list(program.out_schema)
    datas, valids = [], []
    i = 0
    for kd, kv in zip(carry.keys, carry.key_valid):
        dt = fields[i].data_type.jnp_dtype()
        i += 1
        datas.append(jnp.take(kd, sel)[:count].astype(dt))
        valids.append(jnp.take(kv, sel)[:count])
    for (_rk, out_kind, _a), acc, av in zip(program.agg._specs,
                                            carry.accs, carry.acc_valid):
        dt = fields[i].data_type.jnp_dtype()
        i += 1
        datas.append(jnp.take(acc, sel)[:count].astype(dt))
        if out_kind == "count":
            valids.append(jnp.ones((count,), dtype=bool))
        else:
            valids.append(jnp.take(av, sel)[:count])
    return datas, valids, count
