"""Device-resident stage runtime.

`plan/stage_compiler.py` decides WHAT compiles (a StageProgram per
eligible stage pipeline); this package decides HOW it runs: a
persistent jit'd loop that folds a partition's batches in chunks with a
donated agg carry, amortizing Python dispatch per chunk instead of per
batch x operator (loop.py).
"""

from blaze_tpu.runtime.loop import (StageLoopFallback, drain_device,
                                    execute_loop, run_partition)

__all__ = ["StageLoopFallback", "drain_device", "execute_loop",
           "run_partition"]
