"""Array-namespace dispatch: numpy for host-resident batches, jnp on device.

The engine's per-batch glue (padding, masks, promotions, null plumbing)
historically ran as *eager* jax ops.  Each eager dispatch costs ~0.1-1 ms
of XLA program-launch overhead; a SF1 query issues hundreds of them, so
fixed cost — not kernels — dominated the wall clock (BENCH_r03:
vs_baseline 0.297 with roofline_frac 2.6e-05).  The reference has no such
boundary tax: its glue is plain Rust (ref
datafusion-ext-plans/src/common/cached_exprs_evaluator.rs).

The fix mirrors the reference's split between scalar glue and vectorized
kernels: when compute placement pins to host (placement.py), batch columns
stay numpy end-to-end and the glue runs as numpy (nanosecond dispatch,
zero-copy views); the fused hot loops remain jit'd XLA programs, which
accept numpy operands directly.  On a locally-attached accelerator the
columns are jax arrays and everything routes through jnp exactly as
before.  Inside a jit trace operands are tracers, which `xp_of` sends to
jnp — so the same expression code traces unchanged.
"""

from __future__ import annotations

import numpy as np

_jnp = None


def _lazy_jnp():
    global _jnp
    if _jnp is None:
        import jax.numpy as jnp
        _jnp = jnp
    return _jnp


def is_np(a) -> bool:
    """True when `a` is host-resident data (numpy scalar/array, python
    scalar, or None) — anything a jax op is NOT required for."""
    return a is None or isinstance(a, (np.ndarray, np.generic, int, float,
                                       bool, complex))


def xp_of(*arrays):
    """numpy when every operand is host-resident; jnp when any operand is
    a jax array or tracer (including inside jit traces)."""
    for a in arrays:
        if not is_np(a):
            return _lazy_jnp()
    return np


def asnp(a) -> np.ndarray:
    """Pull an array to host numpy (zero-copy for numpy and for CPU-backend
    jax arrays).  Device pulls are accounted as D2H transfer volume."""
    if isinstance(a, np.ndarray):
        return a
    out = np.asarray(a)
    from blaze_tpu.bridge import xla_stats
    xla_stats.note_d2h(out.nbytes)
    return out
