"""Unified memory manager: fixed budget, fair consumer caps, spill-on-pressure.

Parity: auron-memmgr (ref: auron-memmgr/src/lib.rs:38 `MemManager`, `:46`
init, `:82` register_consumer, `:202` `MemConsumer` trait — update_mem_used
triggers spill() of the biggest consumer when the pool overflows).

TPU mapping: the budget models DEVICE HBM held by operator state (sort runs,
agg tables, join build sides, shuffle staging).  Spill tiers mirror the
reference's Spill abstraction (ref auron-memmgr/src/spill.rs:89
try_new_spill: JVM on-heap if available else disk): here tier 1 is host RAM
(the "on-heap" analog — device arrays become numpy/Arrow buffers), tier 2 is
a zstd-compressed disk file.  Synchronous (no condvar): one task runtime
drives one operator chain, so update_mem_used spills inline, matching the
per-task budget discipline rather than the cross-task waiting.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from blaze_tpu import config
from blaze_tpu.memory.spill import SpillMetrics

MEM_SPILL_FACTOR = 0.8  # consumer must shrink below cap*factor after spill


def _trace_spill(consumer, released: int, cause: str) -> None:
    """mem_spill trace instant: which consumer shed how much, why, and
    for which query — the attribution surface's spill-bytes source."""
    try:
        from blaze_tpu.bridge import tracing
        tracing.instant(
            "mem_spill", consumer=consumer.name, bytes=released,
            cause=cause,
            query=getattr(getattr(consumer, "query", None),
                          "query_id", None))
    except Exception:
        pass


class MemConsumer:
    """Spillable operator state (ref MemConsumer trait, lib.rs:202).

    Subclasses implement `spill()` to move their largest retained structure
    down a tier and return the bytes released.
    """

    name: str = "consumer"

    def __init__(self, name: str):
        self.name = name
        self._mem_used = 0
        #: set (under the manager lock) by cross-query arbitration; the
        #: consumer sheds itself on its OWN thread at its next
        #: update_mem_used — a foreign thread must never mutate another
        #: query's operator state mid-batch
        self._release_requested = False
        self._manager: Optional[MemManager] = None
        #: owning serving.QueryContext (captured at set_spillable time);
        #: None for standalone consumers.  Lets the manager arbitrate
        #: ACROSS queries and enforce per-query quotas.
        self.query = None
        self.spill_metrics = SpillMetrics()
        # owning operator's MetricNode; when set, retained-byte peaks are
        # recorded there as `mem_used` (baseline metric vocabulary).  A
        # class may be both ExecutionPlan and MemConsumer — keep the
        # operator MetricNode if one is already attached.
        self.metrics = getattr(self, "metrics", None)

    @property
    def mem_used(self) -> int:
        return self._mem_used

    def set_spillable(self, manager: "MemManager") -> None:
        from blaze_tpu.bridge.context import active_query
        if self.query is None:
            self.query = active_query()
        self._manager = manager
        manager.register_consumer(self)

    def update_mem_used(self, nbytes: int) -> None:
        """Declare current retained bytes; may trigger spills (incl. self)."""
        self._mem_used = max(0, int(nbytes))
        if self.metrics is not None:
            self.metrics.set_max("mem_used", self._mem_used)
        if self._manager is not None:
            self._manager.on_mem_updated(self)

    def add_mem_used(self, delta: int) -> None:
        self.update_mem_used(self._mem_used + delta)

    def spill(self) -> int:
        """Release memory down a tier; returns bytes released."""
        raise NotImplementedError

    def try_release_pressure(self) -> int:
        """Cheaper-than-spill release under pressure, if the consumer has
        one; returns bytes released (0 = nothing cheap, spill() follows).

        The one current implementor is the partial-agg state: with
        auron.tpu.partialAgg.skipping.onSpill it hands its buffered
        partials downstream un-merged (mode switch to pass-through)
        instead of paying spill IO the final stage must re-read anyway."""
        return 0

    def unregister(self) -> None:
        if self._manager is not None:
            self._manager.unregister_consumer(self)
            self._manager = None


class MemManager:
    """Process-wide budget over registered consumers (ref lib.rs:38)."""

    _instance: Optional["MemManager"] = None
    _instance_lock = threading.Lock()

    def __init__(self, total_bytes: int):
        self.total = int(total_bytes)
        self._lock = threading.RLock()
        self._consumers: List[MemConsumer] = []
        self.total_spill_count = 0
        self.total_spilled_bytes = 0
        self.total_pressure_releases = 0
        self.total_quota_breaches = 0
        self.peak_used = 0
        #: per-query shed attribution: query_id (or "<solo>") -> bytes
        #: released on its consumers by pressure/quota arbitration
        self.shed_bytes_by_query: Dict[str, int] = {}
        #: query_id of the first consumer shed under GLOBAL pressure —
        #: the observable form of "the heaviest query pays first"
        self.first_shed_query: Optional[str] = None

    # -- singleton wiring (ref MemManager::init, lib.rs:46) ---------------
    @classmethod
    def init(cls, total_bytes: Optional[int] = None) -> "MemManager":
        with cls._instance_lock:
            if cls._instance is None or total_bytes is not None:
                if total_bytes is None:
                    total_bytes = default_budget_bytes()
                cls._instance = cls(total_bytes)
            return cls._instance

    @classmethod
    def get(cls) -> "MemManager":
        return cls.init()

    # -- consumer registry -------------------------------------------------
    def register_consumer(self, c: MemConsumer) -> None:
        with self._lock:
            if c not in self._consumers:
                self._consumers.append(c)

    def unregister_consumer(self, c: MemConsumer) -> None:
        with self._lock:
            if c in self._consumers:
                self._consumers.remove(c)

    @property
    def mem_used(self) -> int:
        with self._lock:
            return sum(c.mem_used for c in self._consumers)

    def consumer_cap(self) -> int:
        """Fair per-consumer cap: total / max(1, N) (ref lib.rs fair share)."""
        with self._lock:
            return self.total // max(1, len(self._consumers))

    # -- pressure handling -------------------------------------------------
    def on_mem_updated(self, updated: MemConsumer) -> None:
        with self._lock:
            # a pending cross-query release request is honored first, on
            # the consumer's own thread (the only thread that may touch
            # its state)
            if updated._release_requested and updated.mem_used > 0:
                updated._release_requested = False
                released = updated.try_release_pressure()
                if released > 0:
                    self.total_pressure_releases += 1
                else:
                    released = updated.spill()
                    self.total_spill_count += 1
                    self.total_spilled_bytes += released
                    _trace_spill(updated, released, "cross-query-release")
                self._attribute_shed(updated, released,
                                     global_pressure=True)
            used = self.mem_used
            if used > self.peak_used:
                self.peak_used = used
            overflow = used - self.total
            cap = self.consumer_cap()
            # chaos hook: a scripted mem-pressure fault spills the
            # updating consumer as if the pool had overflowed (exercises
            # the spill / re-read path without a real over-budget
            # workload)
            from blaze_tpu import faults
            if faults.fires("mem-pressure") and updated.mem_used > 0:
                released = updated.spill()
                self.total_spill_count += 1
                self.total_spilled_bytes += released
                _trace_spill(updated, released, "injected-pressure")
            # per-query quota first: a query over ITS budget sheds its
            # own state (and climbs the degradation ladder) before its
            # pressure is socialized across the pool
            self._enforce_query_quota(updated)
            # a consumer far over its fair share spills even without global
            # overflow, so one giant sort cannot starve later operators
            if overflow <= 0 and updated.mem_used <= cap * 2:
                return
            # spill biggest consumers until under budget (ref lib.rs: spill
            # of the biggest consumer on pressure).  Across queries the
            # heaviest QUERY pays first (its largest consumer leading), so
            # a light query sharing the pool with a hog is untouched.  A
            # consumer offering a cheaper-than-spill release (partial-agg
            # pass-through switch) is taken at its word first — the
            # released partials stream downstream instead of hitting
            # spill IO.  Consumers of a DIFFERENT query are never shed
            # from this thread (their owner may be mid-mutation): they
            # get a release request they honor at their next update,
            # and because the order is heaviest-first, this thread stops
            # rather than shed its lighter self while the hog's release
            # is pending.
            upd_q = getattr(updated, "query", None)
            for c in self._arbitration_order():
                if self.mem_used <= self.total * MEM_SPILL_FACTOR:
                    break
                if c.mem_used == 0:
                    continue
                c_q = getattr(c, "query", None)
                if c_q is not None and c_q is not upd_q:
                    c._release_requested = True
                    break
                released = c.try_release_pressure()
                if released > 0:
                    self.total_pressure_releases += 1
                    self._attribute_shed(c, released, global_pressure=True)
                    continue
                released = c.spill()
                self.total_spill_count += 1
                self.total_spilled_bytes += released
                _trace_spill(c, released, "pool-pressure")
                self._attribute_shed(c, released, global_pressure=True)

    def _attribute_shed(self, c: MemConsumer, released: int,
                        global_pressure: bool = False) -> None:
        if released <= 0:
            return
        qid = str(getattr(getattr(c, "query", None), "query_id", None)
                  or "<solo>")
        if global_pressure and self.first_shed_query is None:
            self.first_shed_query = qid
        self.shed_bytes_by_query[qid] = (
            self.shed_bytes_by_query.get(qid, 0) + released)

    def _arbitration_order(self) -> List[MemConsumer]:
        """Consumers ordered heaviest-query-first, then biggest-first.

        Standalone consumers (no query) form singleton groups, which
        preserves the single-query behaviour: biggest consumer first.
        """
        totals: Dict[object, int] = {}
        for c in self._consumers:
            q = getattr(c, "query", None)
            key = id(q) if q is not None else ("solo", id(c))
            totals[key] = totals.get(key, 0) + c.mem_used

        def order(c: MemConsumer):
            q = getattr(c, "query", None)
            key = id(q) if q is not None else ("solo", id(c))
            return (-totals[key], -c.mem_used)

        return sorted(self._consumers, key=order)

    def _enforce_query_quota(self, updated: MemConsumer) -> None:
        """Per-query quota: shed the breaching query's own state largest-
        first, and advance its degradation ladder one rung per breaching
        update (pass-through → shrink-capacity → kill)."""
        from blaze_tpu import faults
        q = getattr(updated, "query", None)
        if q is None:
            return
        quota = int(getattr(q, "mem_quota", 0) or 0)
        mine = [c for c in self._consumers if getattr(c, "query", None) is q]
        used = sum(c.mem_used for c in mine)
        forced = faults.fires("quota-breach")
        if not forced and (quota <= 0 or used <= quota):
            return
        self.total_quota_breaches += 1
        rung = q.degrade()
        try:
            from blaze_tpu.bridge import tracing
            tracing.instant("quota_breach", query=q.query_id, used=used,
                            quota=quota, rung=rung)
        except Exception:
            pass
        target = int((quota if quota > 0 else used) * MEM_SPILL_FACTOR)
        for c in sorted(mine, key=lambda c: -c.mem_used):
            if sum(x.mem_used for x in mine) <= target:
                break
            if c.mem_used == 0:
                continue
            released = c.try_release_pressure()
            if released > 0:
                self.total_pressure_releases += 1
                self._attribute_shed(c, released)
                continue
            released = c.spill()
            self.total_spill_count += 1
            self.total_spilled_bytes += released
            _trace_spill(c, released, "query-quota")
            self._attribute_shed(c, released)

    # -- diagnostics (ref lib.rs:143 dump_status) -------------------------
    def dump_status(self) -> str:
        with self._lock:
            lines = [f"MemManager total={self.total} used={self.mem_used} "
                     f"spills={self.total_spill_count} "
                     f"spilled_bytes={self.total_spilled_bytes} "
                     f"pressure_releases={self.total_pressure_releases}"]
            if self.shed_bytes_by_query:
                shed = " ".join(f"{q}={b}" for q, b in
                                sorted(self.shed_bytes_by_query.items()))
                lines.append(f"  shed_by_query: {shed}")
            for c in self._consumers:
                lines.append(f"  {c.name}: used={c.mem_used}")
            return "\n".join(lines)


def default_budget_bytes() -> int:
    """HBM budget: device memory * memory fraction (the executor-overhead ×
    fraction formula of the reference, NativeHelper.scala:51-73)."""
    import jax
    frac = config.MEMORY_FRACTION.get()
    try:
        stats = jax.devices()[0].memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"] * frac)
    except Exception:
        pass
    # CPU fallback: host memory bounded by the process-RSS fraction
    # (ref auron.process.vmrss.memoryFraction), nominally capped at 4 GiB
    try:
        phys = os.sysconf("SC_PHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
    except (ValueError, OSError, AttributeError):
        phys = 4 << 30
    vmrss = config.PROCESS_VMRSS_MEMORY_FRACTION.get()
    return int(min(phys * vmrss, 4 << 30) * frac)
