"""Memory budget + spill tiers (ref: auron-memmgr)."""

from blaze_tpu.memory.manager import MemConsumer, MemManager, default_budget_bytes
from blaze_tpu.memory.spill import (FileSpill, HostMemSpill, Spill,
                                    SpillMetrics, try_new_spill)

__all__ = ["MemConsumer", "MemManager", "default_budget_bytes",
           "FileSpill", "HostMemSpill", "Spill", "SpillMetrics",
           "try_new_spill"]
