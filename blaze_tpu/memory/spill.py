"""Spill abstraction: host-RAM tier and compressed disk-file tier.

Parity: auron-memmgr/src/spill.rs (`:89` try_new_spill chooses JVM on-heap
when available else a direct disk file; `:107` FileSpill, `:180` OnHeapSpill)
and the spill metrics in auron-memmgr/src/metrics.rs.

A Spill stores a sequence of Arrow RecordBatches (the universal operator
state currency) written through the framed compressed IPC writer — the same
format as shuffle blocks (ref io/ipc_compression.rs) so spill files and
shuffle files share one reader.
"""

from __future__ import annotations

import io
import os
import tempfile
import threading
from dataclasses import dataclass, field
from typing import Iterator, List, Optional

import pyarrow as pa

from blaze_tpu import config


@dataclass
class SpillMetrics:
    """(ref auron-memmgr/src/metrics.rs SpillMetrics)"""

    spill_count: int = 0
    spilled_bytes: int = 0        # uncompressed
    spilled_file_bytes: int = 0   # on disk


class Spill:
    """One spilled run of record batches."""

    def write_batches(self, batches: Iterator[pa.RecordBatch]) -> int:
        raise NotImplementedError

    def read_batches(self) -> Iterator[pa.RecordBatch]:
        raise NotImplementedError

    def release(self) -> None:
        pass

    @property
    def stored_bytes(self) -> int:
        raise NotImplementedError


_host_spill_bytes = 0  # live RAM-tier bytes across all spills
_host_spill_lock = threading.Lock()


def _host_spill_account(delta: int) -> None:
    global _host_spill_bytes
    with _host_spill_lock:
        _host_spill_bytes = max(0, _host_spill_bytes + delta)


class HostMemSpill(Spill):
    """Tier-1: device state moved to host RAM as serialized IPC bytes
    (the OnHeapSpill analog, spill.rs:180)."""

    def __init__(self):
        self._buf: Optional[bytes] = None

    def write_batches(self, batches) -> int:
        from blaze_tpu.shuffle.ipc import IpcCompressionWriter
        sink = io.BytesIO()
        w = IpcCompressionWriter(sink)
        n = 0
        for b in batches:
            n += w.write_batch(b)
        w.finish()
        self._buf = sink.getvalue()
        _host_spill_account(len(self._buf))
        return n

    def read_batches(self):
        from blaze_tpu.shuffle.ipc import IpcCompressionReader
        assert self._buf is not None
        yield from IpcCompressionReader(io.BytesIO(self._buf)).read_batches()

    def release(self):
        if self._buf is not None:
            _host_spill_account(-len(self._buf))
        self._buf = None

    @property
    def stored_bytes(self) -> int:
        return len(self._buf) if self._buf else 0


class FileSpill(Spill):
    """Tier-2: compressed on-disk run (ref spill.rs:107 FileSpill)."""

    def __init__(self, dir: Optional[str] = None):
        fd, self.path = tempfile.mkstemp(prefix="blaze-spill-", suffix=".spill",
                                         dir=dir)
        os.close(fd)

    def write_batches(self, batches) -> int:
        from blaze_tpu.shuffle.ipc import IpcCompressionWriter
        n = 0
        with open(self.path, "wb") as f:
            w = IpcCompressionWriter(f)
            for b in batches:
                n += w.write_batch(b)
            w.finish()
        return n

    def read_batches(self):
        from blaze_tpu.shuffle.ipc import IpcCompressionReader
        with open(self.path, "rb") as f:
            yield from IpcCompressionReader(f).read_batches()

    def release(self):
        try:
            os.unlink(self.path)
        except OSError:
            pass

    @property
    def stored_bytes(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0


class HostSpillUnavailable(RuntimeError):
    """The host engine declined a spill allocation (no on-heap room); the
    local tiers take over.  Any OTHER exception from the host factory is
    a real bug and propagates."""


#: Host-engine spill factory installed via the C-ABI callback surface
#: (the OnHeapSpillManager inversion: the engine spills INTO host-managed
#: storage when the host offers it, ref spill.rs:89)
_host_spill_factory = None


def set_host_spill_factory(factory) -> None:
    global _host_spill_factory
    _host_spill_factory = factory


def try_new_spill(prefer_host: bool = True,
                  host_mem_available: Optional[bool] = None) -> Spill:
    """Choose the spill tier (ref spill.rs:89: on-heap if isOnHeapAvailable,
    else getDirectWriteSpillToDiskFile).  A host-engine spill manager
    registered through the C ABI takes precedence; otherwise the RAM tier
    applies up to auron.onHeapSpill.memoryFraction of the manager budget,
    past which runs go straight to disk."""
    factory = _host_spill_factory
    if factory is not None and prefer_host:
        try:
            return factory()
        except HostSpillUnavailable:
            pass  # host refused (no capacity): fall through to local tiers
    if host_mem_available is None:
        if prefer_host:
            from blaze_tpu import config
            from blaze_tpu.memory.manager import MemManager
            cap = (MemManager.get().total *
                   config.ON_HEAP_SPILL_MEMORY_FRACTION.get())
            host_mem_available = _host_spill_bytes < cap
        else:
            host_mem_available = False
    return HostMemSpill() if host_mem_available else FileSpill()
