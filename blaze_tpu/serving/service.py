"""Admission-controlled concurrent query service over the DagScheduler.

The layer Spark provides around the reference engine and Flare-style
native runtimes grow for production: N queries in flight behind a
BOUNDED admission queue, per-tenant quotas, and load shedding — the
service degrades by rejecting (typed `QueryRejected`) under overload,
never by wedging.

Admission pipeline (all under one lock, O(1) per decision):

  1. `admit` fault site — chaos rules shed here (kind="injected");
  2. queue depth vs auron.tpu.serving.maxQueue  (kind="queue-full");
  3. tenant in-flight vs .tenant.maxInflight    (kind="tenant-quota");
  4. scan-bytes estimate vs .admitMemBytes      (kind="memory"; the
     un-stat-able sentinel always admits — shedding needs evidence).

Execution: each admitted query runs on a pool slot inside
`query_scope(ctx)`, so the whole engine below (task pool, batch
iterators, shuffle readers/writers, memory manager) sees its
QueryContext.  Deadline expiry and `cancel()` are observed within one
batch boundary; teardown releases MemConsumer reservations and deletes
shuffle files via the scheduler's concurrent-safe cleanup.
"""

from __future__ import annotations

import threading
import time
import weakref
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional

from blaze_tpu import config, faults
from blaze_tpu.bridge import context as bridge_context
from blaze_tpu.bridge import history, tracing
from blaze_tpu.bridge.context import query_scope
from blaze_tpu.serving.context import QueryCancelled, QueryContext

#: service registry for the profiling HTTP surface (/serving routes)
_services: "weakref.WeakSet[QueryService]" = weakref.WeakSet()


class QueryRejected(RuntimeError):
    """Load-shed at admission; `kind` names which limit fired:
    queue-full | tenant-quota | memory | injected | shutdown."""

    def __init__(self, kind: str, detail: str = ""):
        super().__init__(f"query rejected ({kind})"
                         + (f": {detail}" if detail else ""))
        self.kind = kind


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


class QueryHandle:
    """Caller-side handle: status, result barrier, cancel."""

    def __init__(self, ctx: QueryContext, service: "QueryService"):
        self.ctx = ctx
        self.query_id = ctx.query_id
        self.tenant = ctx.tenant
        self._service = service
        self._done = threading.Event()
        self._result: Any = None
        self._error: Optional[BaseException] = None
        self.status = "queued"  # queued|running|done|failed|cancelled
        self.submitted_at = time.monotonic()
        self.finished_at: Optional[float] = None
        #: DagScheduler.leak_report() of the run, for post-mortem checks
        self.leak_report: Optional[Dict[str, List[str]]] = None
        #: final merged metric tree (dict), populated when the history
        #: plane is on — the event log's terminal payload
        self.metrics_tree: Optional[dict] = None
        #: plan fingerprint of the run, populated when the stats plane
        #: (auron.tpu.stats.enable) is on — keys the statstore record
        #: and the advisor findings in the history finished event
        self.stats_fingerprint: Optional[str] = None
        #: adaptive-execution audit trail: the run's AQE rewrite/seed
        #: events (DagScheduler.aqe_events), [] when AQE never fired
        self.aqe_events: Optional[List[dict]] = None
        #: work-sharing identity: (fingerprint, snapshot) when the plan
        #: is cacheable, and the single-flight key this handle leads
        self._cache_key = None
        self._flight_key: Optional[str] = None

    @property
    def wall_s(self) -> Optional[float]:
        if self.finished_at is None:
            return None
        return self.finished_at - self.submitted_at

    def cancel(self, reason: str = "cancelled by caller") -> bool:
        return self._service.cancel(self.query_id, reason=reason)

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"query {self.query_id} still {self.status} after "
                f"{timeout:g}s")
        if self._error is not None:
            raise self._error
        return self._result

    def exception(self, timeout: Optional[float] = None
                  ) -> Optional[BaseException]:
        if not self._done.wait(timeout):
            raise TimeoutError(f"query {self.query_id} not finished")
        return self._error


class _Flight:
    """One single-flight group: the leader executes, waiters share its
    outcome.  Lives in QueryService._flights under the service lock."""

    __slots__ = ("key", "plan", "leader", "waiters")

    def __init__(self, key: str, plan: Dict[str, Any],
                 leader: QueryHandle):
        self.key = key
        self.plan = plan
        self.leader = leader
        self.waiters: List[QueryHandle] = []


def _default_executor(plan: Dict[str, Any], ctx: QueryContext,
                      handle: Optional[QueryHandle] = None) -> Any:
    """Run one engine-IR plan through a fresh DagScheduler bound to the
    query; cleanup is the scheduler's own (concurrent-safe, reached on
    every exit path), and the leak report lands on the handle."""
    from blaze_tpu.plan.stages import DagScheduler
    sched = DagScheduler(query_ctx=ctx)
    try:
        return sched.run_collect(plan)
    finally:
        sched.cleanup()
        if handle is not None:
            handle.leak_report = sched.leak_report()
            handle.stats_fingerprint = sched.stats_fingerprint
            handle.aqe_events = list(getattr(sched, "aqe_events", []))
            if history.enabled():
                tree = sched.collect_metrics()
                handle.metrics_tree = (tree.to_dict()
                                       if tree is not None else None)


class QueryService:
    """Bounded concurrent query executor with admission control.

    `executor(plan, ctx, handle)` is injectable so unit tests can drive
    admission/cancellation against synthetic workloads; the default runs
    the real staged DagScheduler path.
    """

    def __init__(self, max_concurrent: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 tenant_max_inflight: Optional[int] = None,
                 admit_mem_bytes: Optional[int] = None,
                 executor: Optional[Callable] = None):
        self.max_concurrent = max(1, max_concurrent if max_concurrent
                                  is not None
                                  else config.SERVING_MAX_CONCURRENT.get())
        self.max_queue = max(0, max_queue if max_queue is not None
                             else config.SERVING_MAX_QUEUE.get())
        self.tenant_max_inflight = max(
            1, tenant_max_inflight if tenant_max_inflight is not None
            else config.SERVING_TENANT_MAX_INFLIGHT.get())
        self.admit_mem_bytes = (admit_mem_bytes if admit_mem_bytes
                                is not None
                                else config.SERVING_ADMIT_MEM_BYTES.get())
        self._executor = executor or _default_executor
        self._pool = ThreadPoolExecutor(
            max_workers=self.max_concurrent,
            thread_name_prefix="blaze-serve")
        self._lock = threading.Lock()
        self._handles: Dict[str, QueryHandle] = {}
        self._queued = 0
        self._running = 0
        self._tenant_inflight: Dict[str, int] = {}
        self._tenant_wall_s: Dict[str, List[float]] = {}
        self._closed = False
        self.counters = {"admitted": 0, "completed": 0, "failed": 0,
                         "cancelled": 0, "deadline": 0,
                         "shed_queue_full": 0, "shed_tenant_quota": 0,
                         "shed_memory": 0, "shed_injected": 0,
                         "coalesced": 0, "cache_hits": 0}
        #: single-flight groups keyed by fingerprint:snapshot digest
        self.single_flight = config.SERVING_SINGLE_FLIGHT.get()
        self._flights: Dict[str, _Flight] = {}
        _services.add(self)

    # -- admission ------------------------------------------------------
    def submit(self, plan: Dict[str, Any], *, tenant: str = "default",
               deadline_ms: Optional[float] = None,
               mem_quota: Optional[int] = None,
               query_id: Optional[str] = None) -> QueryHandle:
        if deadline_ms is None:
            deadline_ms = config.QUERY_DEADLINE_MS.get()
        if mem_quota is None:
            mem_quota = config.QUERY_MEM_QUOTA.get()
        # work-sharing identity, computed OUTSIDE the admission lock:
        # the snapshot stats every source file
        cache_key = flight_key = cached_nbytes = None
        if config.CACHE_ENABLE.get() or self.single_flight:
            from blaze_tpu.plan import fingerprint as fp_mod
            cache_key = fp_mod.result_cache_key(plan)
            if cache_key is not None:
                flight_key = (f"{cache_key[0]}:"
                              f"{fp_mod.snapshot_digest(cache_key[1])}")
                if config.CACHE_ENABLE.get():
                    from blaze_tpu.cache import results as result_cache
                    cache = result_cache.get_cache()
                    if cache is not None:
                        cached_nbytes = cache.peek_result_nbytes(
                            cache_key[0], cache_key[1])
        with self._lock:
            if self._closed:
                raise QueryRejected("shutdown", "service is shut down")
            try:
                faults.maybe_fail("admit", tenant=tenant)
            except faults.InjectedFault as e:
                self.counters["shed_injected"] += 1
                raise QueryRejected("injected", str(e)) from e
            if self._queued >= self.max_queue:
                self.counters["shed_queue_full"] += 1
                raise QueryRejected(
                    "queue-full",
                    f"{self._queued} queued >= maxQueue={self.max_queue}")
            inflight = self._tenant_inflight.get(tenant, 0)
            if inflight >= self.tenant_max_inflight:
                self.counters["shed_tenant_quota"] += 1
                raise QueryRejected(
                    "tenant-quota",
                    f"tenant {tenant!r} has {inflight} in flight >= "
                    f"maxInflight={self.tenant_max_inflight}")
            if self.admit_mem_bytes > 0:
                from blaze_tpu.plan.stages import DagScheduler
                est = DagScheduler._scan_input_bytes(plan)
                # a cache hit will serve already-materialized bytes, so
                # the cached footprint supersedes a stale scan estimate
                if cached_nbytes is not None:
                    est = min(est, cached_nbytes)
                # the sentinel (un-stat-able input) always admits:
                # shedding needs evidence, not absence of it
                if est < (1 << 62) and est > self.admit_mem_bytes:
                    self.counters["shed_memory"] += 1
                    raise QueryRejected(
                        "memory",
                        f"estimated {est}B > admitMemBytes="
                        f"{self.admit_mem_bytes}")
            ctx = QueryContext(query_id, tenant=tenant,
                               deadline_ms=deadline_ms or 0,
                               mem_quota=mem_quota or 0)
            handle = QueryHandle(ctx, self)
            handle._cache_key = cache_key
            self._handles[ctx.query_id] = handle
            self._queued += 1
            self._tenant_inflight[tenant] = inflight + 1
            self.counters["admitted"] += 1
            run_now = True
            if self.single_flight and flight_key is not None:
                flight = self._flights.get(flight_key)
                if flight is not None:
                    # identical query already in flight: ride it
                    flight.waiters.append(handle)
                    self.counters["coalesced"] += 1
                    run_now = False
                else:
                    self._flights[flight_key] = _Flight(
                        flight_key, plan, handle)
                    handle._flight_key = flight_key
        # outside the admission lock: the event append does file I/O
        history.note_admitted(ctx.query_id, tenant=tenant,
                              deadline_ms=deadline_ms or 0,
                              mem_quota=mem_quota or 0)
        if run_now:
            self._pool.submit(self._run, handle, plan)
        else:
            from blaze_tpu.bridge import xla_stats
            xla_stats.note_cache(single_flight_coalesces=1)
        return handle

    # -- execution ------------------------------------------------------
    def _run(self, handle: QueryHandle, plan: Dict[str, Any]) -> None:
        ctx = handle.ctx
        queued_s = time.monotonic() - handle.submitted_at
        with self._lock:
            self._queued -= 1
            shed = ctx._cancel_exception() if ctx.cancelled else None
            if shed is None:
                self._running += 1
                handle.status = "running"
            else:
                # cancelled while queued (explicit cancel or deadline
                # passed in the queue): shed at pop, zero work done
                self._finish_locked(handle, error=shed)
                settled = self._settle_flight_locked(handle, shed, None)
        if shed is not None:
            self._maybe_flight_dump(handle)
            self._note_history_finish(handle)
            for w in settled:
                self._maybe_flight_dump(w)
                self._note_history_finish(w)
            return
        history.note_started(ctx.query_id, queued_s=queued_s)
        bridge_context.note_query_start(ctx.query_id)
        error: Optional[BaseException] = None
        result: Any = None
        cache_hit = False
        try:
            with query_scope(ctx), \
                    tracing.execution_context(query=ctx.query_id):
                # the queue wait is a real part of the query's latency:
                # surface it as a span on the query's own trace, measured
                # from submit to pool-slot pop
                tracing.emit_span("admission_wait", int(queued_s * 1e9),
                                  query=ctx.query_id, tenant=ctx.tenant)
                ctx.check()  # deadline may have expired in the queue
                result, cache_hit = self._cached_result(handle)
                if not cache_hit:
                    result = self._executor(plan, ctx, handle)
                    self._store_result(handle, result)
        except BaseException as e:  # noqa: BLE001 - outcome taxonomy below
            error = e
        with self._lock:
            self._running -= 1
            if cache_hit:
                self.counters["cache_hits"] += 1
            self._finish_locked(handle, error=error, result=result)
            settled = self._settle_flight_locked(handle, error, result)
        self._maybe_flight_dump(handle)
        self._note_history_finish(handle)
        for w in settled:
            self._maybe_flight_dump(w)
            self._note_history_finish(w)

    def _cached_result(self, handle: QueryHandle):
        """(result, True) on a semantic result-cache hit — validated
        against the CURRENT source snapshot, so a hit is bit-identical
        to fresh execution; (None, False) otherwise."""
        key = handle._cache_key
        if key is None or not config.CACHE_ENABLE.get():
            return None, False
        from blaze_tpu.cache import results as result_cache
        cache = result_cache.get_cache()
        if cache is None:
            return None, False
        value = cache.get_result(key[0], key[1])
        if value is None:
            return None, False
        tracing.instant("result_cache_hit", query=handle.query_id,
                        fingerprint=key[0])
        return value, True

    def _store_result(self, handle: QueryHandle, result: Any) -> None:
        if (handle._cache_key is None or result is None
                or not config.CACHE_ENABLE.get()):
            return
        from blaze_tpu.cache import results as result_cache
        cache = result_cache.get_cache()
        if cache is not None:
            cache.put_result(handle._cache_key[0],
                             handle._cache_key[1], result)

    def _settle_flight_locked(self, handle: QueryHandle,
                              error: Optional[BaseException],
                              result: Any) -> List[QueryHandle]:
        """Resolve the single-flight group this handle led (no-op for
        non-leaders).  Success and hard failures propagate to every
        waiter; a CANCELLED leader instead promotes the first live
        waiter to executor — its cancellation is its own, not the
        group's, and the cache was never touched by the aborted run.
        Returns the waiters finished here (their history events are the
        caller's, outside the lock)."""
        key = handle._flight_key
        if key is None:
            return []
        flight = self._flights.get(key)
        if flight is None or flight.leader is not handle:
            return []
        settled: List[QueryHandle] = []
        promote = (isinstance(error, QueryCancelled)
                   and not self._closed)
        while promote and flight.waiters:
            w = flight.waiters.pop(0)
            werr = self._waiter_error(w)
            if werr is not None:
                self._queued -= 1
                self._finish_locked(w, error=werr)
                settled.append(w)
                continue
            flight.leader = w
            w._flight_key = key
            try:
                self._pool.submit(self._run, w, flight.plan)
            except RuntimeError:  # pool already shut down
                self._queued -= 1
                self._finish_locked(w, error=QueryCancelled(
                    w.query_id, "service shutdown"))
                settled.append(w)
                continue
            from blaze_tpu.bridge import xla_stats
            xla_stats.note_cache(single_flight_promotions=1)
            return settled
        del self._flights[key]
        for w in flight.waiters:
            self._queued -= 1
            werr = self._waiter_error(w)
            if werr is not None:
                self._finish_locked(w, error=werr)
            elif error is not None:
                self._finish_locked(w, error=error)
            else:
                self._finish_locked(w, result=result)
            settled.append(w)
        return settled

    @staticmethod
    def _waiter_error(w: QueryHandle) -> Optional[BaseException]:
        """A waiter's OWN terminal error (cancel/deadline/quota), if its
        context tripped while it rode the flight — kills stay
        per-query even though execution was shared."""
        try:
            w.ctx.check()
        except BaseException as e:  # noqa: BLE001 - classified by ctx
            return e
        return None

    def _note_history_finish(self, handle: QueryHandle) -> None:
        """Terminal history event (status + metric tree + attribution);
        outside the service lock — the append does file I/O."""
        if not history.enabled():
            return
        err = handle._error
        history.note_finished(
            handle.query_id, status=handle.status, tenant=handle.tenant,
            wall_s=handle.wall_s,
            error=f"{type(err).__name__}: {err}" if err else None,
            metric_tree=handle.metrics_tree,
            fingerprint=handle.stats_fingerprint)

    def _maybe_flight_dump(self, handle: QueryHandle) -> None:
        """Post-mortem: fatally-classified outcomes (deadline, memory
        quota kill, worker pool unavailable) dump the flight recorder.
        Runs outside the service lock — the dump does file I/O."""
        error = handle._error
        if error is None:
            return
        classification = None
        if isinstance(error, QueryCancelled):
            kind = handle.ctx._cancel_kind
            if kind == "deadline":
                classification = "deadline"
            elif kind == "mem":
                classification = "quota-kill"
        else:
            try:
                from blaze_tpu.parallel.workers import WorkerPoolUnavailable
                if isinstance(error, WorkerPoolUnavailable):
                    classification = "pool-unavailable"
            except Exception:
                pass
        if classification is not None:
            bridge_context.record_fatal(handle.query_id, str(error),
                                        classification)

    def _finish_locked(self, handle: QueryHandle,
                       error: Optional[BaseException] = None,
                       result: Any = None) -> None:
        ctx = handle.ctx
        tenant = handle.tenant
        self._tenant_inflight[tenant] = max(
            0, self._tenant_inflight.get(tenant, 1) - 1)
        handle.finished_at = time.monotonic()
        if error is None:
            handle.status = "done"
            handle._result = result
            self.counters["completed"] += 1
            wall = self._tenant_wall_s.setdefault(tenant, [])
            wall.append(handle.wall_s or 0.0)
            del wall[:-1024]  # bounded history
        elif isinstance(error, QueryCancelled):
            handle.status = "cancelled"
            handle._error = error
            if ctx._cancel_kind == "deadline":
                self.counters["deadline"] += 1
            else:
                self.counters["cancelled"] += 1
        else:
            handle.status = "failed"
            handle._error = error
            self.counters["failed"] += 1
        handle._done.set()

    # -- cancellation ---------------------------------------------------
    def cancel(self, query_id: str,
               reason: str = "cancelled by caller") -> bool:
        """Fire the query's token; True if the query was live to cancel.
        The `cancel-race` fault site widens the cancel-vs-completion
        window so chaos runs exercise both orders."""
        handle = self._handles.get(query_id)
        if handle is None:
            return False
        if faults.fires("cancel-race", query=query_id):
            time.sleep(0.02)
        if handle._done.is_set():
            return False
        return handle.ctx.cancel(reason=reason)

    def handle(self, query_id: str) -> Optional[QueryHandle]:
        return self._handles.get(query_id)

    # -- observability --------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            tenants = {}
            for tenant, walls in sorted(self._tenant_wall_s.items()):
                vals = sorted(walls)
                tenants[tenant] = {
                    "completed": len(vals),
                    "p50_ms": round(_percentile(vals, 0.50) * 1e3, 3),
                    "p99_ms": round(_percentile(vals, 0.99) * 1e3, 3)}
            return {"queue_depth": self._queued,
                    "running": self._running,
                    "max_concurrent": self.max_concurrent,
                    "max_queue": self.max_queue,
                    "counters": dict(self.counters),
                    "tenants": tenants}

    # -- lifecycle ------------------------------------------------------
    def shutdown(self, wait: bool = True,
                 cancel_running: bool = False) -> None:
        with self._lock:
            self._closed = True
            handles = list(self._handles.values())
        if cancel_running:
            for h in handles:
                if not h._done.is_set():
                    h.ctx.cancel(reason="service shutdown")
        self._pool.shutdown(wait=wait)

    def __enter__(self) -> "QueryService":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(wait=True, cancel_running=True)


# -- process-wide surface for the profiling HTTP endpoints ---------------

def serving_stats() -> List[Dict[str, Any]]:
    """stats() of every live QueryService in the process."""
    return [svc.stats() for svc in list(_services)]


def cancel_query(query_id: str) -> bool:
    """Cancel by id across every live service (the /serving/cancel
    endpoint); True if some service had the query live."""
    return any(svc.cancel(query_id, reason="cancelled via HTTP")
               for svc in list(_services))


def tenant_wall_samples() -> Dict[str, List[float]]:
    """tenant -> completed-query wall seconds, merged across every live
    service.  Feeds the per-tenant latency histogram in /metrics.prom."""
    merged: Dict[str, List[float]] = {}
    for svc in list(_services):
        with svc._lock:
            for tenant, walls in svc._tenant_wall_s.items():
                merged.setdefault(tenant, []).extend(walls)
    return merged
