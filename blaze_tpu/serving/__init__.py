"""Admission-controlled concurrent query service (ISSUE 7).

The serving layer that turns the single-query engine into a process that
survives "heavy traffic": a bounded admission queue with per-tenant
quotas and load shedding (`service.QueryService`), per-query deadline +
cooperative cancellation + memory-quota degradation (`context
.QueryContext`), and the failure taxonomy callers program against
(`QueryRejected`, `QueryCancelled`, `DeadlineExceeded`,
`QueryMemoryExceeded`).
"""

from blaze_tpu.serving.context import (DeadlineExceeded, QueryCancelled,
                                       QueryContext, QueryMemoryExceeded)
from blaze_tpu.serving.service import (QueryHandle, QueryRejected,
                                       QueryService, cancel_query,
                                       serving_stats)

__all__ = ["QueryContext", "QueryCancelled", "DeadlineExceeded",
           "QueryMemoryExceeded", "QueryService", "QueryHandle",
           "QueryRejected", "serving_stats", "cancel_query"]
