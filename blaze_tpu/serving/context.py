"""Per-query context: deadline, cancellation token, memory quota, degradation.

This module is deliberately stdlib-only (no blaze_tpu imports) so the
bridge / plan / memory layers can use it without import cycles: the
cancellation token has to be visible from ``bridge/context.py`` (a leaf
module) all the way up to ``plan/stages.py``.

Cancellation is *cooperative*: ``QueryContext.check()`` is called at
every task boundary (``bridge/tasks.py``), every metered batch-iterator
step (``ops/base.py``), and every shuffle block read/write.  Cancelling
a query therefore tears it down within one batch, at which point the
normal ``finally`` paths release MemConsumer reservations and the
scheduler's cleanup deletes its shuffle files.

Degradation is a one-way ladder driven by the memory manager when the
query exceeds its quota (see ``memory/manager.py``):

  rung 1  agg-passthrough   force partial-agg pass-through (PR 5)
  rung 2  shrink-capacity   halve the coalesce batch target per rung
  rung 3  kill              cancel the query with QueryMemoryExceeded
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Optional

_query_ids = itertools.count(1)


class QueryCancelled(RuntimeError):
    """The query's cancellation token fired (explicit cancel by default).

    Subclasses refine the reason; ``classify_exception`` in ``faults.py``
    treats RuntimeError as fatal, so a cancelled query is never retried.
    """

    def __init__(self, query_id: str, reason: str = "cancelled"):
        super().__init__(f"query {query_id} {reason}")
        self.query_id = query_id
        self.reason = reason


class DeadlineExceeded(QueryCancelled):
    """The query ran past its deadline."""

    def __init__(self, query_id: str, deadline_ms: float):
        super().__init__(query_id, f"exceeded deadline of {deadline_ms:.0f}ms")
        self.deadline_ms = deadline_ms


class QueryMemoryExceeded(QueryCancelled):
    """The query exhausted its memory quota and the degradation ladder."""

    def __init__(self, query_id: str, quota: int):
        super().__init__(query_id, f"exceeded memory quota of {quota} bytes")
        self.quota = quota


#: degradation rungs, in order; ``degrade()`` returns the rung it entered.
#: Rung 1 also declines the device-resident stage loop (runtime/loop.py
#: checks ``force_agg_passthrough``), and rung 2's capacity shrink halves
#: the loop's chunk width along with the coalesce batch target.
DEGRADE_LADDER = ("agg-passthrough", "shrink-capacity", "kill")


def is_cancellation(exc: BaseException) -> bool:
    """True when ``exc`` means the query is being torn down rather than
    failing: cancellation/deadline/kill must never be swallowed into an
    optimization fallback (device shuffle, rss tier, stage loop) — the
    ONE classifier shared by plan/stages.py and runtime/loop.py so the
    tiers can't drift."""
    from blaze_tpu.bridge.context import TaskKilledError
    return isinstance(exc, (QueryCancelled, TaskKilledError))


class QueryContext:
    """Identity + limits for one query running inside the service.

    Thread-safe: the token is a ``threading.Event`` and the first
    ``cancel()`` wins; every later call is a no-op.  ``check()`` is the
    single cooperative cancellation point — it raises the exception class
    matching the recorded cancel kind.
    """

    def __init__(self, query_id: Optional[str] = None, *,
                 tenant: str = "default",
                 deadline_ms: float = 0.0,
                 mem_quota: int = 0):
        self.query_id = query_id or f"q{next(_query_ids)}"
        self.tenant = tenant
        self.deadline_ms = float(deadline_ms)
        #: absolute monotonic deadline, or None
        self.deadline: Optional[float] = (
            time.monotonic() + self.deadline_ms / 1e3
            if self.deadline_ms > 0 else None)
        self.mem_quota = int(mem_quota)
        self._token = threading.Event()
        self._lock = threading.Lock()
        self._cancel_kind: Optional[str] = None  # "cancel"|"deadline"|"mem"
        self._cancel_reason = ""
        self._degrade_level = 0
        self.started_at = time.monotonic()

    # -- cancellation ---------------------------------------------------
    @property
    def cancelled(self) -> bool:
        return self._token.is_set()

    def cancel(self, reason: str = "cancelled", kind: str = "cancel") -> bool:
        """Fire the token.  Returns True if this call won the race."""
        with self._lock:
            if self._token.is_set():
                return False
            self._cancel_kind = kind
            self._cancel_reason = reason
            self._token.set()
            return True

    def wait_cancelled(self, timeout: float) -> bool:
        """Block up to ``timeout`` seconds; True if the token fired."""
        return self._token.wait(timeout)

    def _cancel_exception(self) -> QueryCancelled:
        if self._cancel_kind == "deadline":
            return DeadlineExceeded(self.query_id, self.deadline_ms)
        if self._cancel_kind == "mem":
            return QueryMemoryExceeded(self.query_id, self.mem_quota)
        return QueryCancelled(self.query_id, self._cancel_reason or "cancelled")

    def remaining_ms(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return (self.deadline - time.monotonic()) * 1e3

    def check(self) -> None:
        """Cooperative cancellation point; raises if cancelled or overdue."""
        if not self._token.is_set() and self.deadline is not None \
                and time.monotonic() > self.deadline:
            self.cancel(kind="deadline")
        if self._token.is_set():
            raise self._cancel_exception()

    # -- degradation ladder --------------------------------------------
    @property
    def degrade_level(self) -> int:
        return self._degrade_level

    @property
    def force_agg_passthrough(self) -> bool:
        return self._degrade_level >= 1

    @property
    def capacity_shrink(self) -> int:
        """How many rungs of batch-capacity halving to apply (>= 0)."""
        return max(0, self._degrade_level - 1)

    def degrade(self) -> str:
        """Advance one rung; rung 3+ cancels the query.  Returns the rung."""
        with self._lock:
            self._degrade_level += 1
            level = self._degrade_level
        if level >= len(DEGRADE_LADDER):
            self.cancel(kind="mem")
            return DEGRADE_LADDER[-1]
        return DEGRADE_LADDER[level - 1]

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        state = "cancelled" if self.cancelled else "live"
        return (f"QueryContext({self.query_id!r}, tenant={self.tenant!r}, "
                f"{state}, degrade={self._degrade_level})")
