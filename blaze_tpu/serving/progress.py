"""Live query-progress registry behind /query/<qid>/progress and the
`python -m blaze_tpu.tools.top` CLI.

The DAG scheduler notes stage starts, per-task completions, and merged
task metrics (rows/bytes) as it runs; `progress(qid)` renders that into
per-stage done/total counts, row/byte rates, and an ETA.  The ETA is
seeded from the statstore prior for the plan fingerprint (p50 wall of
earlier runs) and falls back to fraction-done extrapolation on a cold
fingerprint — the warm-vs-cold accuracy difference is what
`bench.py --obs` measures.

Gated with the rest of the stats plane on `auron.tpu.stats.enable`
(the scheduler checks `statstore.enabled()` before calling in), so the
disabled path allocates nothing.  Stdlib-only; no heavy imports.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["note_query_start", "note_stage_start", "note_stage_replan",
           "note_task_done", "note_rows", "note_query_done", "progress",
           "live", "snapshot_all", "reset"]

_lock = threading.Lock()
_live: Dict[str, Dict[str, Any]] = {}
#: finished snapshots kept for late pollers, insertion-ordered
_done: Dict[str, Dict[str, Any]] = {}
_DONE_CAP = 64
_LIVE_CAP = 256


def note_query_start(query_id: str, fingerprint: Optional[str] = None,
                     prior_wall_s: Optional[float] = None) -> None:
    if not query_id:
        return
    with _lock:
        if len(_live) >= _LIVE_CAP and query_id not in _live:
            return
        _live[query_id] = {
            "query_id": query_id,
            "fingerprint": fingerprint,
            "prior_wall_s": prior_wall_s,
            "t0": time.monotonic(),
            "stages": {},
            "replans": 0,
        }


def note_stage_start(query_id: str, sid: int, tasks: int) -> None:
    with _lock:
        q = _live.get(query_id)
        if q is None:
            return
        st = q["stages"].setdefault(int(sid), {
            "tasks_total": 0, "tasks_done": 0, "rows": 0, "bytes": 0})
        # recovery re-runs re-enter a stage; total counts all attempts
        st["tasks_total"] += max(0, int(tasks))


def note_stage_replan(query_id: str, sid: int, tasks: int) -> None:
    """An AQE rewrite replaced stage `sid`'s plan mid-run (new task
    count `tasks`).  Statstore priors describe the *static* plan's
    wall, so the ETA must stop trusting them and re-estimate from the
    observed completion fraction."""
    with _lock:
        q = _live.get(query_id)
        if q is None:
            return
        q["replans"] = int(q.get("replans", 0)) + 1
        st = q["stages"].get(int(sid))
        if st is not None:
            # the rewrite supersedes the stage's pre-planned tasks:
            # re-baseline total on the not-yet-run portion
            st["tasks_total"] = st["tasks_done"] + max(0, int(tasks))


def note_task_done(query_id: str, sid: int) -> None:
    with _lock:
        q = _live.get(query_id)
        if q is None:
            return
        st = q["stages"].get(int(sid))
        if st is not None:
            st["tasks_done"] += 1


def note_rows(query_id: str, sid: int, rows: int = 0,
              bytes_: int = 0) -> None:
    with _lock:
        q = _live.get(query_id)
        if q is None:
            return
        st = q["stages"].setdefault(int(sid), {
            "tasks_total": 0, "tasks_done": 0, "rows": 0, "bytes": 0})
        st["rows"] += max(0, int(rows))
        st["bytes"] += max(0, int(bytes_))


def _render(q: Dict[str, Any], state: str,
            wall_s: Optional[float] = None) -> Dict[str, Any]:
    elapsed = (wall_s if wall_s is not None
               else time.monotonic() - q["t0"])
    elapsed = max(0.0, float(elapsed))
    stages = {str(sid): dict(st) for sid, st in sorted(q["stages"].items())}
    done = sum(st["tasks_done"] for st in q["stages"].values())
    total = sum(st["tasks_total"] for st in q["stages"].values())
    rows = sum(st["rows"] for st in q["stages"].values())
    nbytes = sum(st["bytes"] for st in q["stages"].values())
    replans = int(q.get("replans", 0))
    eta_s: Optional[float] = None
    eta_source: Optional[str] = None
    if state == "running":
        prior = q.get("prior_wall_s")
        if replans > 0:
            # an AQE rewrite changed the task/partition shape mid-run;
            # the prior described the static plan, so re-estimate from
            # the observed fraction instead
            if total > 0 and 0 < done < total and elapsed > 0:
                eta_s = elapsed * (total - done) / done
                eta_source = "fraction-replanned"
        elif prior is not None and prior > 0:
            eta_s = max(0.0, float(prior) - elapsed)
            eta_source = "prior"
        elif total > 0 and 0 < done < total and elapsed > 0:
            eta_s = elapsed * (total - done) / done
            eta_source = "fraction"
    out: Dict[str, Any] = {
        "query_id": q["query_id"],
        "state": state,
        "fingerprint": q.get("fingerprint"),
        "elapsed_s": round(elapsed, 6),
        "stages": stages,
        "tasks_done": done,
        "tasks_total": total,
        "rows": rows,
        "bytes": nbytes,
        "rows_per_s": round(rows / elapsed, 3) if elapsed > 0 else 0.0,
        "bytes_per_s": round(nbytes / elapsed, 3) if elapsed > 0 else 0.0,
        "eta_s": round(eta_s, 6) if eta_s is not None else None,
        "eta_source": eta_source,
        "replans": replans,
    }
    return out


def note_query_done(query_id: str, status: str = "finished",
                    wall_s: Optional[float] = None) -> None:
    with _lock:
        q = _live.pop(query_id, None)
        if q is None:
            return
        snap = _render(q, "done", wall_s=wall_s)
        snap["status"] = status
        _done[query_id] = snap
        while len(_done) > _DONE_CAP:
            _done.pop(next(iter(_done)))


def progress(query_id: str) -> Optional[Dict[str, Any]]:
    """Current progress for a query: a live rendering while it runs,
    the terminal snapshot after, None if never registered."""
    with _lock:
        q = _live.get(query_id)
        if q is not None:
            return _render(q, "running")
        return dict(_done[query_id]) if query_id in _done else None


def live() -> List[str]:
    with _lock:
        return sorted(_live)


def snapshot_all() -> Dict[str, Any]:
    """The /progress listing: every live query rendered, plus recent
    finished snapshots."""
    with _lock:
        running = [_render(q, "running") for _qid, q in
                   sorted(_live.items())]
        recent = list(_done.values())
    return {"running": running, "recent": recent}


def reset() -> None:
    with _lock:
        _live.clear()
        _done.clear()
