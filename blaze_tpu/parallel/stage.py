"""Fused, fully-jittable stage kernels (static shapes end-to-end).

The eager operator layer (ops/) favors generality: it syncs group counts to
the host per batch.  For the hot TPC-DS shapes the stage compiler fuses
scan-side filter + project + partial aggregation into ONE jit'd function
with a FIXED-capacity group table — no host sync inside the stage, so XLA
fuses the whole pipeline (hash, sort, segmented reduce) into one program.
This mirrors how the reference keeps its whole operator chain inside one
tokio task (rt.rs:156): here the chain lives inside one XLA computation.

Key building block: `partial_agg_table` — sort-based grouping into a
static `num_slots` table (key cols + acc cols + slot validity).  Overflow
slots (more distinct groups than num_slots) spill into a "overflowed"
count the host can check — the AGG_TRIGGER_PARTIAL_SKIPPING analog
(agg_table.rs:108-122): the host reruns the batch through the general
path when it overflows.
"""

from __future__ import annotations

import functools
from functools import partial
from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from blaze_tpu.kernels import compare


class AggTable(NamedTuple):
    """Fixed-capacity columnar group table (the device AccTable)."""

    keys: Tuple[jax.Array, ...]        # each (num_slots,)
    key_valid: Tuple[jax.Array, ...]   # per-key null flags
    accs: Tuple[jax.Array, ...]        # accumulator columns (num_slots,)
    acc_valid: Tuple[jax.Array, ...]
    slot_valid: jax.Array              # (num_slots,) bool
    num_groups: jax.Array              # scalar int32 (may exceed num_slots!)


def sort_by_keys(key_cols: Sequence[Tuple[jax.Array, jax.Array]],
                 valid_mask: jax.Array):
    """Sort rows by (encoded) grouping keys; returns (perm, sorted ops,
    sorted validity)."""
    operands = []
    for data, kvalid in key_cols:
        from blaze_tpu.schema import DataType, TypeId
        bucket, key = compare.order_key(
            data, kvalid, _dtype_of(data), False, True)
        operands.append(bucket)
        operands.append(key)
    perm = compare.lexsort_indices(operands, valid_mask)
    sorted_ops = [jnp.take(o, perm) for o in operands]
    sorted_valid = jnp.take(valid_mask, perm)
    return perm, sorted_ops, sorted_valid


def _dtype_of(data: jax.Array):
    from blaze_tpu import schema as S
    m = {"bool": S.BOOL, "int8": S.INT8, "int16": S.INT16, "int32": S.INT32,
         "int64": S.INT64, "float32": S.FLOAT32, "float64": S.FLOAT64}
    return m[jnp.dtype(data.dtype).name]


def partial_agg_table(key_cols: Sequence[Tuple[jax.Array, jax.Array]],
                      agg_specs: Sequence[Tuple[str, jax.Array, jax.Array]],
                      valid_mask: jax.Array, num_slots: int) -> AggTable:
    """One fused pass: sort rows by key, segment-reduce into a static table.

    agg_specs: (kind, values, validity) with kind in sum/count/min/max.
    Fully traceable — `num_slots` is the only static parameter.
    """
    n = valid_mask.shape[0]
    perm, sorted_ops, sorted_valid = sort_by_keys(key_cols, valid_mask)
    boundary = compare.rows_differ_from_prev(sorted_ops) & sorted_valid
    first_valid = jnp.argmax(sorted_valid)
    boundary = boundary | ((jnp.arange(n) == first_valid) & sorted_valid)
    gids = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    num_groups = jnp.sum(boundary.astype(jnp.int32))
    # rows of groups beyond num_slots scatter out of range (dropped)
    gids = jnp.where(sorted_valid, gids, num_slots)

    keys_out: List[jax.Array] = []
    kvalid_out: List[jax.Array] = []
    for data, kvalid in key_cols:
        sd = jnp.take(data, perm)
        sv = jnp.take(kvalid, perm) & sorted_valid
        # first row of each segment carries the key
        kd = jnp.zeros(num_slots, dtype=data.dtype).at[
            jnp.where(boundary, gids, num_slots)].set(sd, mode="drop")
        kv = jnp.zeros(num_slots, dtype=bool).at[
            jnp.where(boundary, gids, num_slots)].set(sv, mode="drop")
        keys_out.append(kd)
        kvalid_out.append(kv)

    accs_out: List[jax.Array] = []
    avalid_out: List[jax.Array] = []
    for kind, values, avalid in agg_specs:
        sv = jnp.take(values, perm) if values is not None else None
        sav = (jnp.take(avalid, perm) if avalid is not None
               else jnp.ones(n, dtype=bool)) & sorted_valid
        if kind == "count":
            acc = jax.ops.segment_sum(sav.astype(jnp.int64), gids,
                                      num_segments=num_slots)
            accs_out.append(acc)
            avalid_out.append(jnp.ones(num_slots, dtype=bool))
            continue
        if kind == "sum":
            dt = (jnp.float64 if jnp.issubdtype(sv.dtype, jnp.floating)
                  else jnp.int64)
            masked = jnp.where(sav, sv.astype(dt), 0)
            acc = jax.ops.segment_sum(masked, gids, num_segments=num_slots)
        elif kind == "min":
            big = _identity(sv.dtype, False)
            acc = jax.ops.segment_min(jnp.where(sav, sv, big), gids,
                                      num_segments=num_slots)
        elif kind == "max":
            small = _identity(sv.dtype, True)
            acc = jax.ops.segment_max(jnp.where(sav, sv, small), gids,
                                      num_segments=num_slots)
        else:
            raise ValueError(f"unsupported fused agg kind {kind}")
        has = jax.ops.segment_sum(sav.astype(jnp.int32), gids,
                                  num_segments=num_slots) > 0
        acc = jnp.where(has, acc, jnp.zeros_like(acc))
        accs_out.append(acc)
        avalid_out.append(has)

    slot_valid = jnp.arange(num_slots) < jnp.minimum(num_groups, num_slots)
    return AggTable(tuple(keys_out), tuple(kvalid_out), tuple(accs_out),
                    tuple(avalid_out), slot_valid, num_groups)


def pack_dense_keys(key_cols: Sequence[Tuple[jax.Array, jax.Array]],
                    ranges: Sequence[Tuple[int, int]]
                    ) -> Tuple[jax.Array, int]:
    """Pack bounded-range keys into ONE dense group id (row-major strides).

    The TPU fast path: when every grouping key has a known bound — int keys
    with parquet min/max stats, or dictionary codes (always dense) — the
    group id is pure arithmetic and aggregation needs NO SORT, just
    scatter-adds.  Null gets the extra slot per key (range + 1 values).
    Returns (gid array, total_slots)."""
    total = 1
    strides = []
    for lo, hi in ranges:
        strides.append(total)
        total *= (hi - lo + 2)  # +1 for the null slot
    gid = None
    for (data, valid), (lo, hi), stride in zip(key_cols, ranges, strides):
        k = jnp.clip(data.astype(jnp.int64) - lo, 0, hi - lo)
        k = jnp.where(valid, k, hi - lo + 1)
        contrib = k * stride
        gid = contrib if gid is None else gid + contrib
    return gid, total


def pack_dense_keys_i32(key_cols: Sequence[Tuple[jax.Array, jax.Array]],
                        ranges: Sequence[Tuple[int, int]]
                        ) -> Tuple[jax.Array, int]:
    """pack_dense_keys in the 32-bit compute tier: same stride layout,
    all arithmetic in int32 (TPU v5e emulates every 64-bit op as a
    multi-instruction sequence; dense tables are capped far below 2^31
    so the id math never needs the width).  Only the initial `data - lo`
    shift touches the stored key dtype."""
    total = 1
    strides = []
    for lo, hi in ranges:
        strides.append(total)
        total *= (hi - lo + 2)
    assert total < (1 << 31), "dense table exceeds the i32 tier"
    gid = None
    for (data, valid), (lo, hi), stride in zip(key_cols, ranges, strides):
        span = hi - lo
        k = jnp.clip(data - jnp.asarray(lo, dtype=data.dtype),
                     0, span).astype(jnp.int32)
        k = jnp.where(valid, k, jnp.int32(span + 1))
        contrib = k * jnp.int32(stride)
        gid = contrib if gid is None else gid + contrib
    return gid, total


def unpack_dense_keys(slots, ranges: Sequence[Tuple[int, int]], xp=jnp
                      ) -> List[Tuple[jax.Array, jax.Array]]:
    """Inverse of pack_dense_keys for slot indices -> (key, validity).
    Pure stride arithmetic: pass xp=numpy to decode host-side without a
    device round trip."""
    out = []
    rem = slots.astype(xp.int64)
    for lo, hi in ranges:
        size = hi - lo + 2
        k = rem % size
        rem = rem // size
        valid = k < (hi - lo + 1)
        out.append((xp.where(valid, k + lo, 0), valid))
    return out


def dense_partial_agg(gid: jax.Array, num_slots: int,
                      agg_specs: Sequence[Tuple[str, Optional[jax.Array],
                                                Optional[jax.Array]]],
                      valid_mask: jax.Array):
    """Sort-free aggregation: one segment-reduce per accumulator, keyed by
    a precomputed dense group id.  Rows with valid_mask False scatter out
    of range.  Returns (accs, acc_valid, slot_occupied)."""
    g = jnp.where(valid_mask, gid, num_slots)
    accs: List[jax.Array] = []
    avalid: List[jax.Array] = []
    occupied = jax.ops.segment_sum(
        valid_mask.astype(jnp.int32), g, num_segments=num_slots) > 0
    for kind, values, vvalid in agg_specs:
        vv = (vvalid if vvalid is not None
              else jnp.ones_like(valid_mask)) & valid_mask
        if kind == "count":
            acc = jax.ops.segment_sum(vv.astype(jnp.int64), g,
                                      num_segments=num_slots)
            accs.append(acc)
            avalid.append(jnp.ones(num_slots, dtype=bool))
            continue
        if kind == "sum":
            dt = (jnp.float64 if jnp.issubdtype(values.dtype, jnp.floating)
                  else jnp.int64)
            acc = jax.ops.segment_sum(jnp.where(vv, values.astype(dt), 0),
                                      g, num_segments=num_slots)
        elif kind == "min":
            big = _identity(values.dtype, False)
            acc = jax.ops.segment_min(
                jnp.where(vv, values, big),
                jnp.where(vv, g, num_slots), num_segments=num_slots)
        elif kind == "max":
            small = _identity(values.dtype, True)
            acc = jax.ops.segment_max(
                jnp.where(vv, values, small),
                jnp.where(vv, g, num_slots), num_segments=num_slots)
        else:
            raise ValueError(f"unsupported dense agg kind {kind}")
        has = jax.ops.segment_sum(vv.astype(jnp.int32), g,
                                  num_segments=num_slots) > 0
        accs.append(jnp.where(has, acc, jnp.zeros_like(acc)))
        avalid.append(has)
    return accs, avalid, occupied


class HashAggCarry(NamedTuple):
    """Device open-addressing group table (the agg_hash_map.rs analog,
    ref agg_hash_map.rs open-addressing map keyed by grouping bytes).

    TPU-first redesign: linear-probe insertion is expressed as a FIXED
    number of scatter/gather rounds over the whole batch — no sort, no
    per-row loop, no data-dependent shapes.  A multi-operand `lax.sort`
    grouping program takes minutes to compile on TPU; this compiles in
    seconds and runs at HBM speed."""

    keys: Tuple[jax.Array, ...]        # stored key data, each (S,)
    key_valid: Tuple[jax.Array, ...]
    accs: Tuple[jax.Array, ...]
    acc_valid: Tuple[jax.Array, ...]
    used: jax.Array                    # (S,) bool


def init_hash_carry(key_dtypes: Sequence, acc_kinds: Sequence[str],
                    acc_dtypes: Sequence, num_slots: int) -> HashAggCarry:
    keys = tuple(jnp.zeros(num_slots, dtype=dt) for dt in key_dtypes)
    kvalid = tuple(jnp.zeros(num_slots, dtype=bool) for _ in key_dtypes)
    accs, avalid = init_accumulators(acc_kinds, acc_dtypes, num_slots)
    return HashAggCarry(keys, kvalid, accs, avalid,
                        jnp.zeros(num_slots, dtype=bool))


def hash_agg_step(carry: HashAggCarry,
                  key_cols: Sequence[Tuple[jax.Array, jax.Array]],
                  agg_specs: Sequence[Tuple[str, Optional[jax.Array],
                                            Optional[jax.Array]]],
                  mask: jax.Array, probe_rounds: int = 16,
                  lane: Optional[str] = None):
    """Insert one batch into the table.  Returns (new_carry, overflow,
    num_groups); ATOMIC: when any row fails to place within probe_rounds,
    the ORIGINAL carry is returned unchanged (overflow > 0) so the host
    can grow/degrade and retry the whole batch losslessly.

    `lane` picks the probe/claim formulation: 'scatter' (whole-batch
    rounds, the reference), 'pallas'/'interpret' (the VMEM-resident
    placement kernel, kernels/hash_update.py — bit-identical carry by
    construction).  None resolves via kernels/lane.py at trace time;
    jit'd callers resolve it themselves and key their caches with it so
    a knob flip retraces instead of reusing a stale program."""
    from blaze_tpu.kernels import hashing as H
    if lane is None:
        from blaze_tpu.kernels import lane as lane_mod
        lane = lane_mod.resolve("hash")
    S = carry.used.shape[0]
    n = mask.shape[0]
    row_idx = jnp.arange(n, dtype=jnp.int64)

    # grouping normalizes -0.0 to 0.0 AND NaN to one canonical bit
    # pattern BEFORE hashing (Spark's NormalizeFloatingNumbers does both
    # upstream of the hash, so the raw-bits hash kernel itself stays
    # bit-exact with Spark).  Without the NaN leg, differently-encoded
    # NaNs hash to different slots while the slot-match treats any
    # NaN == NaN — keys could land in two groups.
    def _norm(d):
        d = jnp.where(d == 0, jnp.abs(d), d)
        return jnp.where(jnp.isnan(d), jnp.array(jnp.nan, dtype=d.dtype), d)

    key_cols = [(_norm(d), v)
                if jnp.issubdtype(d.dtype, jnp.floating) else (d, v)
                for d, v in key_cols]

    cols = [(d, v, _dtype_of(d).id.value) for d, v in key_cols]
    h = H.hash_columns(cols, seed=42, xp=jnp, algo="xxhash64")
    h = h.astype(jnp.int64) & (S - 1)  # S is a power of two

    used0 = carry.used
    tkeys0 = tuple(carry.keys)
    tkvalid0 = tuple(carry.key_valid)
    placed0 = jnp.full(n, S, dtype=jnp.int64)  # S == unplaced sentinel

    kern = None
    if lane in ("pallas", "interpret"):
        from blaze_tpu.kernels import hash_update as HU
        from blaze_tpu.kernels import lane as lane_mod
        kern = HU.place_rows(h, key_cols, mask, carry, probe_rounds,
                             interpret=(lane == "interpret"))
        if kern is None:  # outside the VMEM envelope -> scatter
            lane_mod.decline("hash", "vmem")

    if kern is not None:
        # placement-only kernel: replay the EXACT legacy tail (key
        # scatters via the claimed slots, used-flag update) so the carry
        # is bit-identical to the scatter formulation's
        placed, wslot = kern
        tkeys = [tk.at[wslot].set(kd, mode="drop")
                 for tk, (kd, _kv) in zip(tkeys0, key_cols)]
        tkvalid = [tv.at[wslot].set(kv, mode="drop")
                   for tv, (_kd, kv) in zip(tkvalid0, key_cols)]
        used = used0.at[wslot].set(True, mode="drop")
        unplaced = mask & (placed == S)
        overflow = jnp.sum(unplaced.astype(jnp.int32))
        return _hash_step_tail(carry, key_cols, agg_specs, mask, placed,
                               tkeys, tkvalid, used, overflow)

    def round_body(state):
        r, used, tkeys, tkvalid, placed, unplaced = state
        slot = (h + r) & (S - 1)
        used_g = jnp.take(used, slot)
        can_claim = unplaced & ~used_g
        # deterministic winner per slot: the lowest row index
        claim = jnp.full(S, n, dtype=jnp.int64).at[
            jnp.where(can_claim, slot, S)].min(row_idx, mode="drop")
        winner = (jnp.take(claim, slot) == row_idx) & can_claim
        wslot = jnp.where(winner, slot, S)
        tkeys = tuple(tk.at[wslot].set(kd, mode="drop")
                      for tk, (kd, _kv) in zip(tkeys, key_cols))
        tkvalid = tuple(tv.at[wslot].set(kv, mode="drop")
                        for tv, (_kd, kv) in zip(tkvalid, key_cols))
        used = used.at[wslot].set(True, mode="drop")
        # match AFTER claims so same-key rows placed this round unify
        eq = jnp.take(used, slot)
        for tk, tv, (kd, kv) in zip(tkeys, tkvalid, key_cols):
            sk = jnp.take(tk, slot)
            sv = jnp.take(tv, slot)
            same = sk == kd
            if jnp.issubdtype(kd.dtype, jnp.floating):
                # grouping treats NaN as equal to NaN (Spark normalizes)
                same = same | (jnp.isnan(sk) & jnp.isnan(kd))
            # SQL grouping: null == null; valid keys compare by value
            eq &= (sv == kv) & jnp.where(kv, same, True)
        ok = unplaced & eq
        placed = jnp.where(ok, slot, placed)
        unplaced = unplaced & ~ok
        return (r + 1, used, tkeys, tkvalid, placed, unplaced)

    def round_cond(state):
        r, _used, _tk, _tv, _placed, unplaced = state
        # early exit: most batches place everything in 1-2 rounds — on
        # the host backend the remaining rounds' S-sized claim arrays
        # would dominate the whole step
        return (r < probe_rounds) & jnp.any(unplaced)

    _r, used, tkeys, tkvalid, placed, unplaced = jax.lax.while_loop(
        round_cond, round_body,
        (jnp.int32(0), used0, tkeys0, tkvalid0, placed0, mask))
    tkeys = list(tkeys)
    tkvalid = list(tkvalid)
    overflow = jnp.sum(unplaced.astype(jnp.int32))
    return _hash_step_tail(carry, key_cols, agg_specs, mask, placed,
                           tkeys, tkvalid, used, overflow)


def _hash_step_tail(carry, key_cols, agg_specs, mask, placed, tkeys,
                    tkvalid, used, overflow):
    """Shared accumulate + atomic-select tail of hash_agg_step: ONE CODE
    PATH for every lane, so accumulator math, null semantics and the
    overflow contract cannot diverge between the scatter formulation and
    the Pallas placement kernel."""
    g = placed  # S sentinel drops out of every scatter below
    new_accs, new_avalid = scatter_accumulate(
        g, [(k, d, v) for k, d, v in agg_specs], mask,
        carry.accs, carry.acc_valid)

    new_carry = HashAggCarry(tuple(tkeys), tuple(tkvalid),
                             tuple(new_accs), tuple(new_avalid), used)
    keep_new = overflow == 0
    sel = jax.tree_util.tree_map(
        lambda nw, old: jnp.where(keep_new, nw, old), new_carry, carry)
    num_groups = jnp.sum(sel.used.astype(jnp.int32))
    return sel, overflow, num_groups


def scatter_accumulate(g: jax.Array,
                       agg_specs: Sequence[Tuple[str, Optional[jax.Array],
                                                 Optional[jax.Array]]],
                       mask: jax.Array, accs: Sequence[jax.Array],
                       avalid: Sequence[jax.Array]):
    """Shared in-place accumulate switch for the dense-gid and hash-table
    carries: rows scatter into slot `g` (out-of-range drops).  Kept in one
    place so null/identity semantics cannot diverge between paths."""
    new_accs, new_avalid = [], []
    for (kind, vd, vv), a, av in zip(agg_specs, accs, avalid):
        cv = (vv if vv is not None else jnp.ones_like(mask)) & mask
        if kind == "count":
            a = a.at[g].add(cv.astype(a.dtype), mode="drop")
        elif kind == "sum":
            a = a.at[g].add(jnp.where(cv, vd.astype(a.dtype), 0),
                            mode="drop")
            av = av.at[g].max(cv, mode="drop")
        elif kind == "min":
            big = _identity(a.dtype, False)
            a = a.at[g].min(jnp.where(cv, vd.astype(a.dtype), big),
                            mode="drop")
            av = av.at[g].max(cv, mode="drop")
        elif kind == "max":
            small = _identity(a.dtype, True)
            a = a.at[g].max(jnp.where(cv, vd.astype(a.dtype), small),
                            mode="drop")
            av = av.at[g].max(cv, mode="drop")
        else:
            raise ValueError(f"unsupported agg kind {kind}")
        new_accs.append(a)
        new_avalid.append(av)
    return new_accs, new_avalid


def init_accumulators(kinds: Sequence[str], acc_dtypes: Sequence,
                      num_slots: int):
    """Identity-initialized accumulator columns (shared by both carries)."""
    accs, avalid = [], []
    for kind, dt in zip(kinds, acc_dtypes):
        if kind == "count":
            accs.append(jnp.zeros(num_slots, dtype=jnp.int64))
            avalid.append(jnp.ones(num_slots, dtype=bool))
            continue
        if kind == "min":
            accs.append(jnp.full(num_slots, _identity(dt, False), dtype=dt))
        elif kind == "max":
            accs.append(jnp.full(num_slots, _identity(dt, True), dtype=dt))
        else:
            accs.append(jnp.zeros(num_slots, dtype=dt))
        avalid.append(jnp.zeros(num_slots, dtype=bool))
    return tuple(accs), tuple(avalid)


def rehash_carry(old: HashAggCarry, kinds: Sequence[str],
                 new_slots: int, probe_rounds: int = 16,
                 lane: Optional[str] = None):
    """Re-insert an existing table into a larger one (the grow path).
    `kinds` are the ORIGINAL accumulator kinds; stored accumulators
    re-merge with merge semantics (count -> sum of counts)."""
    key_dtypes = [k.dtype for k in old.keys]
    acc_dtypes = [a.dtype for a in old.accs]
    fresh = init_hash_carry(key_dtypes, kinds, acc_dtypes, new_slots)
    specs = [("sum" if k == "count" else k, a, av)
             for k, a, av in zip(kinds, old.accs, old.acc_valid)]
    return hash_agg_step(fresh, list(zip(old.keys, old.key_valid)), specs,
                         old.used, probe_rounds, lane=lane)


def merge_agg_tables(table: AggTable,
                     merge_kinds: Sequence[str], num_slots: int) -> AggTable:
    """Re-aggregate a (possibly duplicated-key) table — the partial_merge
    phase as a fused kernel.  Input slots act as rows."""
    key_cols = list(zip(table.keys, table.key_valid))
    specs = []
    for kind, acc, av in zip(merge_kinds, table.accs, table.acc_valid):
        k = "sum" if kind in ("count", "sum") else kind
        specs.append((k, acc, av))
    return partial_agg_table(key_cols, specs, table.slot_valid, num_slots)


def _identity(dtype, minimum: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(-jnp.inf if minimum else jnp.inf, dtype=dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.min if minimum else info.max, dtype=dtype)


# ---------------------------------------------------------------------------
# Device-resident exchange: the shard_map stage runner behind the
# DagScheduler's device shuffle.  The reference repartitions map output
# through shuffle files + BlockManager RPC; on a mesh the same
# repartition is ONE collective program — every device hash-partitions
# its local rows with the Spark-compatible pid, stages them into
# bucket-ladder-padded per-destination buffers, and `lax.all_to_all`
# moves every partition simultaneously over ICI.  The file shuffle
# (shuffle/writer.py) stays behind it as the spill + fault-tolerance
# fallback: any failure here raises and the scheduler re-runs the stage
# through the file path, where PR 4's lineage recovery applies.


class DeviceExchangeError(RuntimeError):
    """The device-resident exchange declined or failed.  The scheduler
    catches this (and any other exchange-side error), bumps
    `shuffle_device_fallbacks`, and re-runs the stage through the host
    file shuffle — device shuffle is an optimization, never a new
    failure mode."""


@functools.lru_cache(maxsize=64)
def _exchange_program(mesh, n_out: int, capacity: int,
                      key_idx: Tuple[int, ...], dtypes: Tuple[str, ...],
                      lane: str = "scatter"):
    """Build + cache the jit'd shard_map exchange for one static shape.

    Cache key = (mesh, reduce partition count, bucket-ladder rung, key
    column positions, column dtype signature, partition lane): the
    collective compiles once per rung and is reused by every batch that
    lands on it; the lane rides the key so a knob flip retraces.
    """
    from jax.sharding import PartitionSpec as PS

    from blaze_tpu.bridge.xla_stats import meter_jit
    from blaze_tpu.parallel.collective import (all_to_all_rows,
                                               partition_ids_for_keys)
    from blaze_tpu.parallel.mesh import DP_AXIS, shard_map_compat

    n_dev = mesh.shape[DP_AXIS]
    ncols = len(dtypes)

    def stage(row_valid, *cols):
        datas = cols[:ncols]
        valids = cols[ncols:]
        keys = [(datas[i], valids[i]) for i in key_idx]
        pid = partition_ids_for_keys(keys, n_out).astype(jnp.int32)
        # reduce partition r is served by device r % n_dev; the pid
        # column rides the exchange so the host can split received rows
        # back into exact reduce partitions
        dev = pid % n_dev
        out_cols, out_valid, overflow = all_to_all_rows(
            list(datas) + list(valids) + [pid],
            row_valid, dev, DP_AXIS, n_dev, capacity, lane=lane)
        return tuple(out_cols) + (out_valid, overflow.reshape(1))

    sharded = shard_map_compat(stage, mesh, PS(DP_AXIS), PS(DP_AXIS))
    return meter_jit(sharded, name="mesh.exchange_rows")


def _pad_rows(a, total: int, dtype=None):
    """Zero-pad one column to `total` rows.  Host (numpy) input pads in
    numpy; device (jax) input — the stage loop's D2D drain — pads with
    jnp.pad so it never leaves the device."""
    n = int(a.shape[0])
    if isinstance(a, np.ndarray):
        buf = np.zeros(total, dtype=dtype or a.dtype)
        buf[:n] = a
        return buf
    import jax.numpy as jnp
    out = jnp.pad(a, (0, total - n))
    return out.astype(dtype) if dtype is not None else out


class ExchangeTicket:
    """One in-flight device exchange: the UNAWAITED outputs of the
    first-rung dispatch plus everything `DeviceExchange.drain` needs to
    finish the job — the remaining capacity-ladder rungs (with the
    padded send buffers kept alive for an overflow re-dispatch), the
    per-rung accounting accumulated so far, and the host-split
    metadata.  Produced by `dispatch`, consumed exactly once by
    `drain`; between the two the collective and the D2D partition
    routing are free to run while the host folds the next chunk."""

    __slots__ = ("out", "rungs", "row_valid", "datas", "vbufs",
                 "key_idx", "dtypes", "lane", "n", "ncols", "n_out",
                 "n_dev", "rows_per_dev", "ctx", "moved_bytes",
                 "collectives", "dispatch_ns", "parts")

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw.get(k))


class DeviceExchange:
    """Host-side driver for the on-device repartition.

    Pads the map output to a static per-device row count (so sharding
    splits evenly), dispatches the cached `_exchange_program` at a
    bucket-ladder capacity rung sized for `auron.tpu.mesh.exchangeSkew`,
    climbs to the next rung when a destination bucket overflows (the
    final rung = per-device row count can never overflow), and splits
    the received rows back into per-reduce-partition columns in a
    deterministic (destination, source, slot) order.

    The driver is split into `dispatch` (everything through issuing the
    first rung's shard_map call — returns an ExchangeTicket holding the
    unawaited device futures) and `drain` (the overflow host sync, the
    rung climb, accounting, and the host split).  `exchange` composes
    the two back-to-back, which IS the synchronous path byte-for-byte;
    the overlapped scheduler (plan/stages.py) instead drains ticket k
    on a background thread while task k+1 is still folding.
    """

    def __init__(self, mesh=None):
        if mesh is None:
            from blaze_tpu.parallel.mesh import current_mesh
            mesh = current_mesh()
        self.mesh = mesh

    def exchange(self, columns: Sequence[np.ndarray],
                 valids: Sequence[np.ndarray],
                 key_indices: Sequence[int], n_out: int, ctx: str = ""):
        """columns/valids: per-column (data, bool validity) arrays of
        one common length n — numpy from the staged collect, or device
        (jax) arrays straight from the stage loop's drain (runtime/
        loop.py), which stay on device through padding and sharding
        (D2D, no host round trip).  Returns `parts`: n_out entries of
        ([data...], [valid...]) holding that reduce partition's rows."""
        return self.drain(self.dispatch(columns, valids, key_indices,
                                        n_out, ctx=ctx))

    def dispatch(self, columns: Sequence[np.ndarray],
                 valids: Sequence[np.ndarray],
                 key_indices: Sequence[int], n_out: int,
                 ctx: str = "") -> ExchangeTicket:
        """Issue the all-to-all WITHOUT awaiting it: pad, pick the
        ladder rungs, fire the per-shard fault sites, and dispatch the
        first rung's cached program.  Returns immediately — jax
        dispatch is async, so the returned ticket's `out` arrays are
        device futures the collective is still filling."""
        import time as _time

        from blaze_tpu import config, faults
        from blaze_tpu.batch import bucket_capacity, bucket_ladder
        from blaze_tpu.parallel.collective import exchange_wire_cost
        from blaze_tpu.parallel.mesh import DP_AXIS, shard_rows

        ncols = len(columns)
        if ncols == 0:
            raise DeviceExchangeError("no columns to exchange")
        n = int(len(columns[0]))
        n_dev = int(self.mesh.shape[DP_AXIS])
        if n == 0:
            parts = [([np.zeros(0, c.dtype) for c in columns],
                      [np.zeros(0, dtype=bool) for _ in columns])
                     for _ in range(n_out)]
            return ExchangeTicket(parts=parts, n=0, ncols=ncols,
                                  n_out=int(n_out), n_dev=n_dev,
                                  ctx=ctx, rungs=[], moved_bytes=0,
                                  collectives=0,
                                  dispatch_ns=_time.perf_counter_ns())

        # pad to n_dev * rows_per_dev so NamedSharding splits evenly;
        # padding rows carry row_valid=False and are never sent
        rows_per_dev = bucket_capacity(-(-n // n_dev))
        total = n_dev * rows_per_dev
        row_valid = np.zeros(total, dtype=bool)
        row_valid[:n] = True
        datas = [_pad_rows(c, total) for c in columns]
        vbufs = [_pad_rows(v, total, dtype=bool) for v in valids]

        # capacity ladder: start at skew * expected rows/destination,
        # retry the next rung on overflow; rows_per_dev (= every local
        # row routed to ONE destination) is the guaranteed-fit ceiling
        skew = max(1.0, config.MESH_EXCHANGE_SKEW.get())
        expect = -(-rows_per_dev // n_dev)
        start = bucket_capacity(max(int(expect * skew), 1))
        rungs = [c for c in bucket_ladder(rows_per_dev) if c >= start]
        if not rungs:
            rungs = [start]
        if rungs[-1] < rows_per_dev:
            rungs.append(bucket_capacity(rows_per_dev))

        key_idx = tuple(int(i) for i in key_indices)
        dtypes = tuple(np.dtype(c.dtype).name for c in columns)
        from blaze_tpu.kernels import lane as lane_mod
        lane = lane_mod.resolve("partition")

        cap = rungs[0]
        # the scripted mid-collective kill: one decision per shard
        # per dispatch, so `device-collective@k` targets shard k-1
        for d in range(n_dev):
            faults.maybe_fail("device-collective", shard=d, stage=ctx)
        fn = _exchange_program(self.mesh, int(n_out), int(cap),
                               key_idx, dtypes, lane)
        out = fn(*shard_rows(self.mesh, row_valid, *datas, *vbufs))
        moved_bytes, collectives = exchange_wire_cost(n_dev, cap, dtypes)
        return ExchangeTicket(
            out=out, rungs=list(rungs[1:]), row_valid=row_valid,
            datas=datas, vbufs=vbufs, key_idx=key_idx, dtypes=dtypes,
            lane=lane, n=n, ncols=ncols, n_out=int(n_out), n_dev=n_dev,
            rows_per_dev=rows_per_dev, ctx=ctx, moved_bytes=moved_bytes,
            collectives=collectives,
            dispatch_ns=_time.perf_counter_ns())

    def drain(self, ticket: ExchangeTicket):
        """Await a dispatched exchange: block on the overflow scalar
        (the one host sync), climb the remaining ladder rungs when a
        destination bucket overflowed (re-firing the per-shard fault
        sites per re-dispatch, exactly like the synchronous loop), then
        split the received rows into per-partition numpy columns."""
        from blaze_tpu import faults
        from blaze_tpu.bridge import xla_stats
        from blaze_tpu.parallel.collective import exchange_wire_cost
        from blaze_tpu.parallel.mesh import shard_rows

        if ticket.parts is not None:
            return ticket.parts
        ncols, n_out = ticket.ncols, ticket.n_out
        out = ticket.out
        result = None
        while True:
            overflow = int(np.sum(np.asarray(out[-1])))
            if overflow == 0:
                result = out
                break
            if not ticket.rungs:
                break
            cap = ticket.rungs.pop(0)
            for d in range(ticket.n_dev):
                faults.maybe_fail("device-collective", shard=d,
                                  stage=ticket.ctx)
            fn = _exchange_program(self.mesh, n_out, int(cap),
                                   ticket.key_idx, ticket.dtypes,
                                   ticket.lane)
            out = fn(*shard_rows(self.mesh, ticket.row_valid,
                                 *ticket.datas, *ticket.vbufs))
            mb, cc = exchange_wire_cost(ticket.n_dev, cap, ticket.dtypes)
            ticket.moved_bytes += mb
            ticket.collectives += cc
        if result is None:
            raise DeviceExchangeError(
                f"destination bucket overflow persisted through the "
                f"ladder (rows_per_dev={ticket.rows_per_dev})")
        xla_stats.note_device_exchange(ticket.n, ticket.moved_bytes,
                                       ticket.collectives)

        out_cols = [np.asarray(a) for a in result[:ncols]]
        out_vals = [np.asarray(a).astype(bool)
                    for a in result[ncols:2 * ncols]]
        pid_r = np.asarray(result[2 * ncols])
        valid_r = np.asarray(result[2 * ncols + 1]).astype(bool)

        # received layout is already (dest device, source device, slot)
        # deterministic; a stable sort by pid keeps it reproducible
        pids = pid_r[valid_r]
        order = np.argsort(pids, kind="stable")
        bounds = np.searchsorted(pids[order], np.arange(n_out + 1))
        datas_live = [c[valid_r][order] for c in out_cols]
        vals_live = [v[valid_r][order] for v in out_vals]
        parts = []
        for r in range(n_out):
            lo, hi = int(bounds[r]), int(bounds[r + 1])
            parts.append(([d[lo:hi] for d in datas_live],
                          [v[lo:hi] for v in vals_live]))
        ticket.parts = parts
        ticket.out = ticket.datas = ticket.vbufs = None  # free buffers
        return parts
