"""Device mesh construction + the distributed stage runner.

TPU-native scaling model (SURVEY.md §7 step 7): data parallelism over a 1-D
`dp` mesh axis (each device = one partition worth of rows, the Spark-task
analog), with exchanges as in-jit collectives over ICI.  Multi-host slices
extend the same mesh across hosts (jax.distributed); the host shuffle
service (shuffle/) carries cross-slice DCN traffic.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"


def make_mesh(num_devices: Optional[int] = None,
              axis: str = DP_AXIS) -> Mesh:
    devs = jax.devices()
    n = num_devices or len(devs)
    return Mesh(np.array(devs[:n]), (axis,))


def shard_rows(mesh: Mesh, *arrays: jax.Array):
    """Shard row-dimension arrays across the dp axis."""
    sharding = NamedSharding(mesh, P(DP_AXIS))
    return tuple(jax.device_put(a, sharding) for a in arrays)


def distributed_grouped_agg(mesh: Mesh, key_specs, agg_specs,
                            num_slots: int, out_slots: int,
                            merge_kinds: Sequence[str]):
    """Build the jit'd two-phase distributed aggregation step.

    Returns fn(valid_mask, *key_and_value_arrays) -> final AggTable slots
    per device.  The whole pipeline — partial agg, on-device hash
    partition, ICI all-to-all, final merge — is ONE compiled XLA program:
    the TPU-native equivalent of map-side agg + shuffle + reduce-side agg.

    key_specs / agg_specs describe argument positions:
      key_specs: number of key columns (each contributes data+valid args)
      agg_specs: list of kinds ('sum'|'count'|'min'|'max'); each non-count
                 contributes data+valid args.
    """
    from blaze_tpu.parallel.collective import all_to_all_regroup
    from blaze_tpu.parallel.stage import merge_agg_tables, partial_agg_table

    num_keys = key_specs if isinstance(key_specs, int) else len(key_specs)
    P_ = mesh.shape[DP_AXIS]

    def stage(valid_mask, *cols):
        i = 0
        keys = []
        for _ in range(num_keys):
            keys.append((cols[i], cols[i + 1]))
            i += 2
        specs = []
        for kind in agg_specs:
            if kind == "count":
                specs.append((kind, None, None))
            else:
                specs.append((kind, cols[i], cols[i + 1]))
                i += 2
        local = partial_agg_table(keys, specs, valid_mask, num_slots)
        received = all_to_all_regroup(local, DP_AXIS, P_, out_slots)
        final = merge_agg_tables(received, merge_kinds, out_slots)
        # scalars can't concatenate across the mesh: give num_groups a
        # (1,)-axis so out_specs P('dp') stacks per-device counts
        return final._replace(num_groups=final.num_groups.reshape(1))

    sharded = jax.shard_map(
        stage, mesh=mesh,
        in_specs=P(DP_AXIS),
        out_specs=P(DP_AXIS),
        check_vma=False)
    return jax.jit(sharded)


def distributed_broadcast_join_agg(mesh: Mesh, build_capacity: int):
    """Broadcast-hash-join + grouped aggregation as ONE SPMD program.

    The build side REPLICATES to every device (broadcast = replication,
    SURVEY §2.7; the NativeBroadcastExchangeBase analog) pre-sorted by
    key; probe rows shard across the dp axis.  Each device matches its
    probe shard with a vectorized binary search (the same sorted-build
    discipline as kernels/join), scatter-accumulates sum/count per build
    slot into a local dense table, and a `psum` over ICI merges the
    partials — every device ends with the complete per-build-key
    aggregates, one dispatch, zero host round trips.

    Returns fn(build_keys_sorted, probe_keys, probe_valid, probe_vals)
    -> (sums[build_capacity], counts[build_capacity]), replicated.

    PRECONDITION: build_keys_sorted must be sorted AND unique — the
    binary search credits one slot per key, so duplicate build keys
    would silently undercount (callers dedup with np.unique).
    """
    def stage(build_keys, probe_keys, probe_valid, probe_vals):
        idx = jnp.searchsorted(build_keys, probe_keys)
        idx = jnp.clip(idx, 0, build_capacity - 1)
        matched = probe_valid & (build_keys[idx] == probe_keys)
        slot = jnp.where(matched, idx, build_capacity)
        sums = jnp.zeros(build_capacity, jnp.float64) \
            .at[slot].add(jnp.where(matched, probe_vals, 0.0),
                          mode="drop")
        counts = jnp.zeros(build_capacity, jnp.int64) \
            .at[slot].add(matched.astype(jnp.int64), mode="drop")
        return (jax.lax.psum(sums, DP_AXIS),
                jax.lax.psum(counts, DP_AXIS))

    sharded = jax.shard_map(
        stage, mesh=mesh,
        in_specs=(P(), P(DP_AXIS), P(DP_AXIS), P(DP_AXIS)),
        out_specs=(P(), P()),
        check_vma=False)
    return jax.jit(sharded)
