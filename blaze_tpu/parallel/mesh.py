"""Device mesh construction + the distributed stage runner.

TPU-native scaling model (SURVEY.md §7 step 7): data parallelism over a 1-D
`dp` mesh axis (each device = one partition worth of rows, the Spark-task
analog), with exchanges as in-jit collectives over ICI.  Multi-host slices
extend the same mesh across hosts (jax.distributed); the host shuffle
service (shuffle/) carries cross-slice DCN traffic.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from blaze_tpu.bridge.xla_stats import meter_jit

DP_AXIS = "dp"


def shard_map_compat(fn, mesh: Mesh, in_specs, out_specs):
    """jax.shard_map across jax versions.  Newer jax exposes it at the
    top level with `check_vma`; 0.4.x only has
    jax.experimental.shard_map with the older `check_rep` flag.  Both
    checks are disabled for the same reason: the collective programs
    here intentionally mix per-device and replicated intermediates."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def make_mesh(num_devices: Optional[int] = None,
              axis: str = DP_AXIS) -> Mesh:
    devs = jax.devices()
    n = num_devices or len(devs)
    return Mesh(np.array(devs[:n]), (axis,))


_mesh_cache: dict = {}


def current_mesh() -> Mesh:
    """The process-wide dp mesh, sized by `auron.tpu.mesh.devices`
    (0 = every visible device).  Cached per size: Mesh construction is
    cheap but mesh IDENTITY keys the jit cache, so handing out a fresh
    Mesh per exchange would recompile every collective program."""
    from blaze_tpu import config
    visible = len(jax.devices())
    n = config.MESH_DEVICES.get() or visible
    n = max(1, min(int(n), visible))
    m = _mesh_cache.get(n)
    if m is None:
        m = _mesh_cache[n] = make_mesh(n)
    return m


def shard_rows(mesh: Mesh, *arrays: jax.Array):
    """Shard row-dimension arrays across the dp axis."""
    sharding = NamedSharding(mesh, P(DP_AXIS))
    return tuple(jax.device_put(a, sharding) for a in arrays)


def distributed_grouped_agg(mesh: Mesh, key_specs, agg_specs,
                            num_slots: int, out_slots: int,
                            merge_kinds: Sequence[str]):
    """Build the jit'd two-phase distributed aggregation step.

    Returns fn(valid_mask, *key_and_value_arrays) -> final AggTable slots
    per device.  The whole pipeline — partial agg, on-device hash
    partition, ICI all-to-all, final merge — is ONE compiled XLA program:
    the TPU-native equivalent of map-side agg + shuffle + reduce-side agg.

    key_specs / agg_specs describe argument positions:
      key_specs: number of key columns (each contributes data+valid args)
      agg_specs: list of kinds ('sum'|'count'|'min'|'max'); each non-count
                 contributes data+valid args.
    """
    from blaze_tpu.parallel.collective import all_to_all_regroup
    from blaze_tpu.parallel.stage import merge_agg_tables, partial_agg_table

    num_keys = key_specs if isinstance(key_specs, int) else len(key_specs)
    P_ = mesh.shape[DP_AXIS]

    def stage(valid_mask, *cols):
        i = 0
        keys = []
        for _ in range(num_keys):
            keys.append((cols[i], cols[i + 1]))
            i += 2
        specs = []
        for kind in agg_specs:
            if kind == "count":
                specs.append((kind, None, None))
            else:
                specs.append((kind, cols[i], cols[i + 1]))
                i += 2
        local = partial_agg_table(keys, specs, valid_mask, num_slots)
        received = all_to_all_regroup(local, DP_AXIS, P_, out_slots)
        final = merge_agg_tables(received, merge_kinds, out_slots)
        # scalars can't concatenate across the mesh: give num_groups a
        # (1,)-axis so out_specs P('dp') stacks per-device counts
        return final._replace(num_groups=final.num_groups.reshape(1))

    sharded = shard_map_compat(stage, mesh, P(DP_AXIS), P(DP_AXIS))
    return meter_jit(sharded, name="mesh.grouped_agg")


def distributed_sort(mesh: Mesh, num_payloads: int, capacity: int,
                     samples_per_device: int = 64, descending: bool = False):
    """Globally range-partitioned sort as ONE SPMD program.

    The reference's global sort is range-repartition (driver-sampled
    bounds, NativeShuffleExchangeBase.scala:313) + per-partition external
    sort.  The on-mesh form does all of it inside one jit: each device
    samples its local keys, an `all_gather` shares the samples, every
    device derives identical quantile bounds, rows ride the raw-row
    all-to-all to their range partition, and a local sort finishes.
    After the step, device i's valid rows are all <= device i+1's
    (reversed when `descending`) and each device is locally sorted.

    Returns fn(keys, valid, *payloads) -> (keys', valid', *payloads',
    overflow) with per-device length `num_devices * capacity`.  Keys must
    be a numeric dtype; nulls (valid=False) are not emitted.
    """
    from blaze_tpu.parallel.collective import all_to_all_rows

    P_ = mesh.shape[DP_AXIS]
    S = samples_per_device

    def _encode(keys):
        """(sort_key, nan_rank, is_nan): sort_key ascends in the requested
        order.  Integers/bool invert via bitwise NOT (negation wraps
        INT64_MIN and unsigned dtypes); float NaN zeroes out of the value
        key and rides a separate rank — Spark treats NaN as the LARGEST
        value (last on ASC, first on DESC)."""
        if jnp.issubdtype(keys.dtype, jnp.floating):
            nan = jnp.isnan(keys)
            base = jnp.where(nan, jnp.zeros_like(keys), keys)
            skey = -base if descending else base
            rank_nan = 0 if descending else 1
            nan_rank = jnp.where(nan, rank_nan, 1 - rank_nan) \
                .astype(jnp.int32)
            return skey, nan_rank, nan
        skey = ~keys if descending else keys
        return skey, jnp.zeros(keys.shape, jnp.int32), \
            jnp.zeros(keys.shape, bool)

    def stage(keys, valid, *payloads):
        if len(payloads) != num_payloads:
            raise ValueError(
                f"distributed_sort built for {num_payloads} payload "
                f"columns, got {len(payloads)}")
        R = keys.shape[0]
        sort_key, nan_rank, is_nan = _encode(keys)
        # sample only finite valid keys (NaN routes to a fixed partition
        # below; nulls are never emitted)
        finite = valid & ~is_nan
        not_finite = (~finite).astype(jnp.int32)
        _, key_s = jax.lax.sort((not_finite, sort_key), num_keys=2)
        n_fin = jnp.sum(finite.astype(jnp.int32))
        pos = (jnp.arange(S) * jnp.maximum(n_fin, 1)) // S
        pos = jnp.clip(pos, 0, R - 1)
        samp = jnp.take(key_s, pos)
        samp_valid = jnp.arange(S) < jnp.minimum(n_fin, S)

        all_samp = jax.lax.all_gather(samp, DP_AXIS).reshape(P_ * S)
        all_sv = jax.lax.all_gather(samp_valid, DP_AXIS).reshape(P_ * S)
        sinv, ssort = jax.lax.sort(((~all_sv).astype(jnp.int32), all_samp),
                                   num_keys=2)
        m = jnp.sum(all_sv.astype(jnp.int32))
        bpos = (jnp.arange(1, P_) * jnp.maximum(m, 1)) // P_
        bounds = jnp.take(ssort, jnp.clip(bpos, 0, P_ * S - 1))

        pid = jnp.searchsorted(bounds, sort_key, side="right")
        # NaN = largest: last device on ASC order, first on DESC
        pid = jnp.where(is_nan, 0 if descending else P_ - 1, pid)
        cols, valid_r, overflow = all_to_all_rows(
            [keys] + list(payloads), valid,
            pid.astype(jnp.int32), DP_AXIS, P_, capacity)
        keys_r, payloads_r = cols[0], cols[1:]
        skey_r, nan_rank_r, _ = _encode(keys_r)
        # total order: (invalid-last, NaN rank, value key), carried perm
        _, _, _, perm = jax.lax.sort(
            ((~valid_r).astype(jnp.int32), nan_rank_r, skey_r,
             jnp.arange(valid_r.shape[0], dtype=jnp.int32)), num_keys=3)
        out_keys = jnp.take(keys_r, perm)
        out_valid = jnp.take(valid_r, perm)
        out_payloads = [jnp.take(p, perm) for p in payloads_r]
        return tuple([out_keys, out_valid] + out_payloads +
                     [overflow.reshape(1)])

    sharded = shard_map_compat(stage, mesh, P(DP_AXIS), P(DP_AXIS))
    return meter_jit(sharded, name="mesh.sort")


def distributed_hash_join(mesh: Mesh, num_build_payloads: int,
                          num_probe_payloads: int, capacity: int,
                          pair_cap: int):
    """Shuffled hash join (inner equi-join) as ONE SPMD program.

    Both sides hash-partition by Spark-compatible pmod(murmur3(key, 42))
    on device, ride the raw-row all-to-all so equal keys co-locate, and
    each device runs a local sorted-probe join (sort build side, binary
    search per probe row, bounded pair expansion — the same discipline as
    kernels/join.py, kept inside the SPMD program).

    Returns fn(bkeys, bvalid, *bpayloads, pkeys, pvalid, *ppayloads) ->
    (jkeys, jvalid, *bpayloads', *ppayloads', counts) per device, where
    `counts` = [local pair total, build overflow, probe overflow] lets
    the host detect capacity misses (re-run bigger, never silent).
    """
    from blaze_tpu.kernels.join import expand_pairs
    from blaze_tpu.parallel.collective import (all_to_all_rows,
                                               partition_ids_for_keys)

    P_ = mesh.shape[DP_AXIS]
    NB, NP = num_build_payloads, num_probe_payloads

    def stage(*args):
        bkeys, bvalid = args[0], args[1]
        bpay = list(args[2:2 + NB])
        pkeys, pvalid = args[2 + NB], args[3 + NB]
        ppay = list(args[4 + NB:4 + NB + NP])

        # float NaN keys are treated as null HERE: NaN sorts after the
        # +inf padding sentinel and would break the valid-prefix
        # invariant below.  Spark's NaN == NaN join semantics belong to
        # the caller: canonicalize NaN keys to one bit pattern (the
        # planner's key normalization) before the exchange.
        if jnp.issubdtype(bkeys.dtype, jnp.floating):
            bvalid = bvalid & ~jnp.isnan(bkeys)
        if jnp.issubdtype(pkeys.dtype, jnp.floating):
            pvalid = pvalid & ~jnp.isnan(pkeys)

        bpid = partition_ids_for_keys([(bkeys, bvalid)], P_)
        ppid = partition_ids_for_keys([(pkeys, pvalid)], P_)
        bcols, bval_r, bovf = all_to_all_rows(
            [bkeys] + bpay, bvalid, bpid, DP_AXIS, P_, capacity)
        pcols, pval_r, povf = all_to_all_rows(
            [pkeys] + ppay, pvalid, ppid, DP_AXIS, P_, capacity)
        bk, bp = bcols[0], bcols[1:]
        pk, pp = pcols[0], pcols[1:]

        # local sorted-probe join: invalid build keys become a +max
        # sentinel so the sorted array is GLOBALLY ascending (searchsorted
        # needs monotonicity; merely parking invalids last would restart
        # the key order mid-array)
        n = bk.shape[0]
        sentinel = (jnp.inf if jnp.issubdtype(bk.dtype, jnp.floating)
                    else jnp.iinfo(bk.dtype).max)
        bk_masked = jnp.where(bval_r, bk, sentinel)
        # secondary key: invalid-last, so a VALID row whose real key
        # equals the sentinel still sorts before the masked padding and
        # the [0, n_build) prefix is exactly the valid rows
        bk_s, _, bperm = jax.lax.sort(
            (bk_masked, (~bval_r).astype(jnp.int32),
             jnp.arange(n, dtype=jnp.int32)), num_keys=2)
        n_build = jnp.sum(bval_r.astype(jnp.int32))
        lo = jnp.searchsorted(bk_s, pk, side="left")
        hi = jnp.searchsorted(bk_s, pk, side="right")
        # matches beyond the valid prefix are parked invalid rows
        hi = jnp.minimum(hi, n_build)
        count = jnp.where(pval_r, jnp.maximum(hi - lo, 0), 0)
        p_idx, b_sorted_pos, pair_valid, total = expand_pairs(
            lo.astype(jnp.int64), count.astype(jnp.int64), pair_cap)
        b_idx = jnp.take(bperm, jnp.clip(b_sorted_pos, 0, n - 1))

        jkeys = jnp.take(pk, p_idx)
        out_b = [jnp.take(col, b_idx) for col in bp]
        out_p = [jnp.take(col, p_idx) for col in pp]
        # raw total (NOT clamped): total > pair_cap tells the host pairs
        # were dropped — capacity misses must never look like exact fits
        counts = jnp.stack([total.astype(jnp.int64),
                            bovf.astype(jnp.int64),
                            povf.astype(jnp.int64)])
        return tuple([jkeys, pair_valid] + out_b + out_p +
                     [counts.reshape(3)])

    sharded = shard_map_compat(stage, mesh, P(DP_AXIS), P(DP_AXIS))
    return meter_jit(sharded, name="mesh.hash_join")


def distributed_broadcast_join_agg(mesh: Mesh, build_capacity: int):
    """Broadcast-hash-join + grouped aggregation as ONE SPMD program.

    The build side REPLICATES to every device (broadcast = replication,
    SURVEY §2.7; the NativeBroadcastExchangeBase analog) pre-sorted by
    key; probe rows shard across the dp axis.  Each device matches its
    probe shard with a vectorized binary search (the same sorted-build
    discipline as kernels/join), scatter-accumulates sum/count per build
    slot into a local dense table, and a `psum` over ICI merges the
    partials — every device ends with the complete per-build-key
    aggregates, one dispatch, zero host round trips.

    Returns fn(build_keys_sorted, probe_keys, probe_valid, probe_vals)
    -> (sums[build_capacity], counts[build_capacity]), replicated.

    PRECONDITION: build_keys_sorted must be sorted AND unique — the
    binary search credits one slot per key, so duplicate build keys
    would silently undercount (callers dedup with np.unique).
    """
    def stage(build_keys, probe_keys, probe_valid, probe_vals):
        idx = jnp.searchsorted(build_keys, probe_keys)
        idx = jnp.clip(idx, 0, build_capacity - 1)
        matched = probe_valid & (build_keys[idx] == probe_keys)
        slot = jnp.where(matched, idx, build_capacity)
        sums = jnp.zeros(build_capacity, jnp.float64) \
            .at[slot].add(jnp.where(matched, probe_vals, 0.0),
                          mode="drop")
        counts = jnp.zeros(build_capacity, jnp.int64) \
            .at[slot].add(matched.astype(jnp.int64), mode="drop")
        return (jax.lax.psum(sums, DP_AXIS),
                jax.lax.psum(counts, DP_AXIS))

    sharded = shard_map_compat(stage, mesh,
                               (P(), P(DP_AXIS), P(DP_AXIS), P(DP_AXIS)),
                               (P(), P()))
    return meter_jit(sharded, name="mesh.broadcast_join_agg")
