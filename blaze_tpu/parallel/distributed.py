"""Multi-host execution: jax.distributed initialization + a cross-process
host-shuffle service.

Parity mapping (SURVEY §5 distributed-communication backend):

  * INTRA-slice, on-device: mesh collectives over ICI (parallel/mesh.py —
    psum/all-to-all inside jit).  Multi-HOST meshes come from
    `init_distributed`, after which `jax.devices()` spans every process
    and the existing mesh/pjit code runs unchanged — XLA routes
    collectives over ICI within a slice and DCN across slices.
  * CROSS-process, host-side: the reference rides Spark's BlockManager /
    an RSS (shuffle/rss.rs:45).  `HostShuffleService` is that transport
    with the SAME `.data`/`.index` file contract: every process writes
    its map outputs into a shared directory (NFS/FUSE/object-store
    mount), commits with a marker file, and reducers wait for all maps
    before reading their file segments.  Because the on-disk format is
    identical to the single-process exchange, a plan does not change
    shape when it crosses hosts — only the block source does.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional

from blaze_tpu.shuffle.reader import FileSegmentBlock


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> int:
    """Initialize jax.distributed so `jax.devices()` spans all hosts
    (the NCCL/MPI bootstrap analog; jax reads JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID when args are None).  Returns the
    global device count."""
    import jax
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    return len(jax.devices())


class HostShuffleService:
    """Directory-backed cross-process shuffle exchange.

    Layout under `root` for one shuffle:
        shuffle-<id>-<map>.data / .index   (the AuronShuffleWriterBase
                                            contract, :46-85)
        shuffle-<id>-<map>.commit          (map-completion marker; the
                                            MapStatus analog)
    """

    def __init__(self, root: str, shuffle_id: str, num_maps: int,
                 num_reduces: int):
        self.root = root
        self.shuffle_id = shuffle_id
        self.num_maps = num_maps
        self.num_reduces = num_reduces
        os.makedirs(root, exist_ok=True)

    # -- map side -----------------------------------------------------------
    def map_output_paths(self, map_id: int):
        base = os.path.join(self.root,
                            f"shuffle-{self.shuffle_id}-{map_id}")
        return base + ".data", base + ".index"

    def commit_map(self, map_id: int) -> None:
        """Publish a finished map output (atomic via rename)."""
        base = os.path.join(self.root,
                            f"shuffle-{self.shuffle_id}-{map_id}")
        tmp = base + ".commit.tmp"
        with open(tmp, "w") as f:
            f.write("ok")
        os.replace(tmp, base + ".commit")

    # -- reduce side --------------------------------------------------------
    def wait_for_maps(self, timeout_s: float = 60.0,
                      poll_s: float = 0.05) -> None:
        """Block until every map has committed (the shuffle barrier)."""
        deadline = time.monotonic() + timeout_s
        while True:
            missing = [m for m in range(self.num_maps)
                       if not os.path.exists(os.path.join(
                           self.root,
                           f"shuffle-{self.shuffle_id}-{m}.commit"))]
            if not missing:
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"shuffle {self.shuffle_id}: maps {missing} not "
                    f"committed within {timeout_s}s")
            time.sleep(poll_s)

    def blocks_for(self, reduce_id: int) -> List[FileSegmentBlock]:
        from blaze_tpu.shuffle.exchange import read_index_file
        out = []
        for m in range(self.num_maps):
            data, index = self.map_output_paths(m)
            offsets = read_index_file(index)
            length = offsets[reduce_id + 1] - offsets[reduce_id]
            if length > 0:
                out.append(FileSegmentBlock(data, offsets[reduce_id],
                                            length))
        return out

    def register_reader(self, resource_id: str) -> None:
        """Expose this shuffle's blocks through the resource map so
        IpcReaderExec plans can consume it by id."""
        from blaze_tpu.bridge.resource import put_resource
        put_resource(resource_id, self.blocks_for)

    def cleanup(self) -> None:
        for m in range(self.num_maps):
            base = os.path.join(self.root,
                                f"shuffle-{self.shuffle_id}-{m}")
            for p in (base + ".data", base + ".index", base + ".commit"):
                try:
                    os.unlink(p)
                except OSError:
                    pass
