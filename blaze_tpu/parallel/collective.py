"""Mesh collectives: the TPU-native exchange (shuffle-over-ICI).

The reference's all-to-all exchange is shuffle files + BlockManager RPC
(SURVEY.md §2.7).  On a TPU slice, the same repartitioning rides ICI as an
XLA `all_to_all` INSIDE the jit'd stage: every device hash-partitions its
local group table by key, scatters slots into per-destination buffers, and
one collective moves all partitions simultaneously.  Global (ungrouped)
aggregates merge with a single `psum`.  Host shuffle files remain the
cross-slice / cross-host fallback (DCN), exactly how the reference keeps
RSS as the wide-area transport.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from blaze_tpu.kernels import hashing as H
from blaze_tpu.parallel.stage import AggTable, merge_agg_tables


def partition_ids_for_keys(keys: Sequence[Tuple[jax.Array, jax.Array]],
                           num_partitions: int) -> jax.Array:
    """Spark-compatible pid = pmod(murmur3(normalize(keys), 42), P) on
    device (ref shuffle/mod.rs:164-189) — traceable under jit/shard_map.
    Delegates to the ONE shared definition (H.spark_partition_ids) so
    the device lane and the host file-shuffle path agree bit-for-bit on
    where every key lives (incl. -0.0/NaN float normalization)."""
    from blaze_tpu.parallel.stage import _dtype_of
    flat_cols = []
    tids = []
    for data, valid in keys:
        flat_cols.append((data, valid))
        tids.append(_dtype_of(data).id.value)
    return H.spark_partition_ids(flat_cols, tids, num_partitions, xp=jnp)


def _dest_slots(pid: jax.Array, num_partitions: int, capacity: int,
                lane: str = "scatter"):
    """Dense within-destination slot assignment for per-destination
    buffers of `capacity` rows.

    Returns (order, dest, overflow): `order` sorts rows by destination;
    `dest` = (partition, slot) per sorted row, routed OUT of bounds for
    rows with pid >= num_partitions or past capacity, so scatters with
    mode="drop" discard them instead of clobbering a live slot;
    `overflow` counts in-range rows dropped by the capacity limit.

    lane 'pallas'/'interpret' takes the radix partition kernel
    (kernels/radix.py) instead of the stable argsort: there `order` is
    None and `dest` is per ORIGINAL row (callers skip the take) — the
    scattered buffers and the overflow count are bit-identical."""
    R = pid.shape[0]
    if lane in ("pallas", "interpret"):
        from blaze_tpu.kernels import lane as lane_mod
        from blaze_tpu.kernels import radix
        if radix.vmem_estimate(R, num_partitions) <= lane_mod.vmem_budget():
            return radix.dest_slots(pid, num_partitions, capacity,
                                    interpret=(lane == "interpret"))
        lane_mod.decline("partition", "vmem")
    order = jnp.argsort(pid, stable=True)
    sorted_pid = jnp.take(pid, order)
    counts = jnp.bincount(jnp.clip(pid, 0, num_partitions),
                          length=num_partitions + 1)[:num_partitions]
    starts = jnp.cumsum(counts) - counts
    idx_within = jnp.arange(R) - jnp.take(
        jnp.concatenate([starts, jnp.zeros(1, starts.dtype)]),
        jnp.clip(sorted_pid, 0, num_partitions))
    sendable = sorted_pid < num_partitions
    in_range = sendable & (idx_within < capacity)
    overflow = jnp.sum((sendable & ~in_range).astype(jnp.int32))
    dest = (jnp.where(in_range, sorted_pid, num_partitions),
            jnp.where(in_range, idx_within, capacity))
    return order, dest, overflow


def all_to_all_regroup(table: AggTable, axis_name: str,
                       num_partitions: int, out_slots: int,
                       lane: str = "scatter") -> AggTable:
    """Exchange group-table slots so equal keys land on one device, then
    merge — the on-ICI shuffle+final-agg.  Callable only inside shard_map
    over `axis_name`."""
    G = table.slot_valid.shape[0]
    pid = partition_ids_for_keys(
        list(zip(table.keys, table.key_valid)), num_partitions)
    pid = jnp.where(table.slot_valid, pid, num_partitions)  # park empties

    # per-destination capacity G: a device's slots can never overflow it
    order, dest, _overflow = _dest_slots(pid, num_partitions, G, lane)

    def scatter(col):
        sc = jnp.take(col, order) if order is not None else col
        buf = jnp.zeros((num_partitions, G), dtype=col.dtype)
        return buf.at[dest].set(sc, mode="drop")

    def scatter_valid(col):
        sc = jnp.take(col, order) if order is not None else col
        buf = jnp.zeros((num_partitions, G), dtype=bool)
        return buf.at[dest].set(sc, mode="drop")

    keys_b = [scatter(k) for k in table.keys]
    kval_b = [scatter_valid(v) for v in table.key_valid]
    accs_b = [scatter(a) for a in table.accs]
    aval_b = [scatter_valid(v) for v in table.acc_valid]
    slot_b = scatter_valid(table.slot_valid)

    def exchange(buf):
        return jax.lax.all_to_all(buf, axis_name, split_axis=0,
                                  concat_axis=0, tiled=False)

    keys_r = [exchange(b).reshape(num_partitions * G) for b in keys_b]
    kval_r = [exchange(b).reshape(num_partitions * G) for b in kval_b]
    accs_r = [exchange(b).reshape(num_partitions * G) for b in accs_b]
    aval_r = [exchange(b).reshape(num_partitions * G) for b in aval_b]
    slot_r = exchange(slot_b).reshape(num_partitions * G)

    received = AggTable(tuple(keys_r), tuple(kval_r), tuple(accs_r),
                        tuple(aval_r), slot_r,
                        jnp.sum(slot_r.astype(jnp.int32)))
    # kinds: sum-merge semantics chosen by caller via merge_agg_tables
    return received


def all_to_all_rows(columns: Sequence[jax.Array], valid: jax.Array,
                    pid: jax.Array, axis_name: str, num_partitions: int,
                    capacity: int, lane: str = "scatter"):
    """Operator-agnostic raw-row exchange over ICI.

    The reference's repartitioner moves arbitrary operator output rows
    (shuffle/mod.rs:55-123) — not just agg tables.  This is the on-mesh
    analog: every device routes each of its local rows to the device
    `pid[r]` names, staging them into per-destination buffers of static
    `capacity`, and ONE `lax.all_to_all` moves every partition
    simultaneously.  Callable only inside shard_map over `axis_name`.

    columns: per-row data arrays, each shape (rows,).
    valid:   (rows,) bool — invalid rows are not sent.
    pid:     (rows,) int destination in [0, num_partitions).

    Returns (columns', valid', overflow):
      columns' each (num_partitions * capacity,) — received rows, padded;
      valid' marks the real ones; overflow counts LOCAL rows dropped
      because a destination bucket exceeded `capacity` (callers re-run
      with a bigger bucket when nonzero — the same bounded-overflow
      discipline as the fused agg table)."""
    pid = jnp.where(valid, pid, num_partitions)  # park unsent rows
    order, dest, overflow = _dest_slots(pid, num_partitions, capacity,
                                        lane)

    def exchange(buf):
        return jax.lax.all_to_all(buf, axis_name, split_axis=0,
                                  concat_axis=0, tiled=False)

    out_cols = []
    for col in columns:
        sc = jnp.take(col, order) if order is not None else col
        buf = jnp.zeros((num_partitions, capacity), dtype=col.dtype)
        buf = buf.at[dest].set(sc, mode="drop")
        out_cols.append(exchange(buf).reshape(num_partitions * capacity))
    vbuf = jnp.zeros((num_partitions, capacity), dtype=bool)
    vbuf = vbuf.at[dest].set(True, mode="drop")
    out_valid = exchange(vbuf).reshape(num_partitions * capacity)
    return out_cols, out_valid, overflow


def exchange_wire_cost(n_dev: int, capacity: int,
                       dtypes: Sequence[str]) -> Tuple[int, int]:
    """Accounting for ONE all_to_all_rows dispatch at `capacity`: every
    device stages (n_dev dests x capacity) send buffers per exchanged
    column — the data columns + their bool validity columns + the int32
    pid rider + the bool row mask — and the program issues one
    collective per buffer.  Returns (moved_bytes, collectives);
    DeviceExchange sums these per ladder rung for
    xla_stats.note_device_exchange, identically for the synchronous
    exchange and the overlapped dispatch/drain split."""
    import numpy as np
    ncols = len(dtypes)
    per_slot = sum(np.dtype(d).itemsize for d in dtypes) + ncols + 4 + 1
    return n_dev * n_dev * capacity * per_slot, 2 * ncols + 2


def psum_table_accs(table: AggTable, axis_name: str) -> AggTable:
    """Global (ungrouped) aggregate merge: one psum over acc columns."""
    accs = tuple(jax.lax.psum(jnp.where(v, a, jnp.zeros_like(a)), axis_name)
                 for a, v in zip(table.accs, table.acc_valid))
    any_valid = tuple(jax.lax.psum(v.astype(jnp.int32), axis_name) > 0
                      for v in table.acc_valid)
    return table._replace(accs=accs, acc_valid=any_valid)
