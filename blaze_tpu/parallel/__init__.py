"""Mesh / collective exchange (TPU-native: ICI all-to-all, psum)."""

from blaze_tpu.parallel.collective import (all_to_all_regroup,
                                           all_to_all_rows,
                                           partition_ids_for_keys,
                                           psum_table_accs)
from blaze_tpu.parallel.mesh import (DP_AXIS, current_mesh,
                                     distributed_broadcast_join_agg,
                                     distributed_grouped_agg,
                                     distributed_hash_join,
                                     distributed_sort,
                                     make_mesh, shard_rows)
from blaze_tpu.parallel.stage import (AggTable, DeviceExchange,
                                      DeviceExchangeError,
                                      merge_agg_tables,
                                      partial_agg_table)

__all__ = ["all_to_all_regroup", "all_to_all_rows",
           "partition_ids_for_keys",
           "psum_table_accs", "DP_AXIS", "distributed_grouped_agg",
           "distributed_broadcast_join_agg", "distributed_hash_join",
           "distributed_sort",
           "make_mesh", "shard_rows", "current_mesh",
           "AggTable", "DeviceExchange", "DeviceExchangeError",
           "merge_agg_tables", "partial_agg_table"]
