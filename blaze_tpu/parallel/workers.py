"""Supervised worker-process pool: crash fault domains for map tasks.

Every task in the engine used to run on a ThreadPoolExecutor inside ONE
Python process, so a native XLA segfault, kernel OOM-kill, or hung
dispatch took down the whole query service.  The reference engine gives
each task a native runtime inside a JVM executor process that Spark
supervises and restarts; this module is that executor runtime for
blaze_tpu:

- `WorkerPool` spawns N long-lived child processes
  (`python -m blaze_tpu.parallel.workers --child`) and ships tasks to
  them over a length-prefixed pipe protocol reusing the CRC-framed wire
  format from shuffle/ipc.py (same header/CRC structs, so a torn or
  bit-rotted frame is detected, not deserialized).
- Children heartbeat while running a task; a busy worker silent past
  `auron.tpu.workers.livenessMs` is declared hung, SIGKILLed, and its
  task re-dispatched (the executor-heartbeat analog).
- A dead child's exit status is classified into `WorkerCrashed`
  (negative rc = signal), which faults.classify_exception treats as
  RETRYABLE; the crashed worker's id rides along so the retry can land
  on a DIFFERENT worker (bridge/tasks.py excludes it).
- Crashed slots restart with exponential backoff; a slot that exceeds
  `auron.tpu.workers.crashBudget` is blacklisted and never receives
  tasks again (the excludeOnFailure analog).
- Cancellation / per-call deadlines propagate as a cancel message, then
  escalate SIGTERM -> SIGKILL; cancel kills do NOT count against the
  crash budget (the worker was healthy, the query was not).
- Crash listeners let the DAG scheduler invalidate the dead worker's
  entries in the map-output table so FetchFailedError lineage recovery
  re-runs only the poisoned producers (plan/stages.py).

Fallback matrix: the pool is opt-in (`auron.tpu.workers.enable`); when
it is off, cannot spawn, or is fully blacklisted, callers fall back to
the in-process thread path (`WorkerPoolUnavailable`), which stays the
seed-verified baseline.
"""

from __future__ import annotations

import importlib
import io
import logging
import os
import pickle
import queue
import re
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set

from blaze_tpu import faults
from blaze_tpu.bridge import tracing
from blaze_tpu.faults import FetchFailedError, WorkerCrashed, \
    classify_exception
from blaze_tpu.shuffle.ipc import CODEC_RAW, FLAG_CRC, _check_frame_byte, \
    _CRC, _decompress, _HEADER, _verify_crc, pack_control_frame

log = logging.getLogger("blaze_tpu.workers")


class WorkerPoolUnavailable(RuntimeError):
    """The pool cannot take this task (disabled, spawn failed, closed,
    or every slot blacklisted).  Callers fall back to running the task
    in-process on the thread path."""


class RemoteTaskError(RuntimeError):
    """A task raised inside a worker and the exception type could not be
    (or should not be) reconstructed parent-side.  Carries the child's
    verdict in `remote_classify` so faults.classify_exception preserves
    retryable/fatal semantics across the process boundary."""

    def __init__(self, message: str, remote_classify: str = "fatal"):
        super().__init__(message)
        self.remote_classify = remote_classify


# ---------------------------------------------------------------------------
# Pipe framing: pickled control/result messages ride the shuffle IPC
# frame format ([codec|FLAG_CRC][u32 len][u32 crc32c][payload]) so a
# truncated or corrupted frame surfaces as a checksum/EOF error the
# retry machinery already classifies, never as a bad unpickle.

def _frame_codec() -> int:
    """The wire codec for OUTGOING control frames: io.compression.codec
    when io.compression.workerFrames opts the worker protocol in, raw
    otherwise.  Each frame self-describes its codec in the header byte,
    so mixed parent/child settings (the conf snapshot lands only with
    the first task) interoperate frame-by-frame."""
    from blaze_tpu import config
    if not config.IO_COMPRESSION_WORKER_FRAMES.get():
        return CODEC_RAW
    from blaze_tpu.shuffle.ipc import _get_codec
    return _get_codec()


def _send_msg(fp, obj: Any, lock: Optional[threading.Lock] = None) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    codec = _frame_codec()
    frame = pack_control_frame(payload, codec)
    if codec != CODEC_RAW:
        saved = (_HEADER.size + _CRC.size + len(payload)) - len(frame)
        if saved > 0:
            from blaze_tpu.bridge import xla_stats
            xla_stats.note_frame_compression("worker", saved)
    if lock is not None:
        with lock:
            fp.write(frame)
            fp.flush()
    else:
        fp.write(frame)
        fp.flush()


def _read_exact(fp, n: int) -> Optional[bytes]:
    buf = io.BytesIO()
    got = 0
    while got < n:
        chunk = fp.read(n - got)
        if not chunk:
            return None if got == 0 else b""
        buf.write(chunk)
        got += len(chunk)
    return buf.getvalue()


def _recv_msg(fp) -> Optional[Any]:
    """Read one framed message; None on clean EOF, EOFError on a torn
    frame, ShuffleChecksumError on CRC mismatch."""
    header = _read_exact(fp, _HEADER.size)
    if header is None:
        return None
    if header == b"":
        raise EOFError("truncated worker-pipe frame header")
    raw_codec, length = _HEADER.unpack(header)
    codec = _check_frame_byte(raw_codec)
    crc = None
    if raw_codec & FLAG_CRC:
        crc_bytes = _read_exact(fp, _CRC.size)
        if not crc_bytes:
            raise EOFError("truncated worker-pipe frame checksum")
        (crc,) = _CRC.unpack(crc_bytes)
    payload = _read_exact(fp, length)
    if payload is None or len(payload) != length:
        raise EOFError("truncated worker-pipe frame payload")
    if crc is not None:
        _verify_crc(crc, payload)
    if codec != CODEC_RAW:
        # CRC covers the wire bytes (corruption detection happens before
        # any codec touches them); the codec byte keys the decode
        payload = _decompress(codec, payload)
    return pickle.loads(payload)


# ---------------------------------------------------------------------------
# Parent side

_STARTING = "starting"
_IDLE = "idle"
_BUSY = "busy"
_DEAD = "dead"
_BLACKLISTED = "blacklisted"


class _Slot:
    """One supervised worker slot: a process incarnation plus its crash
    history.  The slot survives its processes — crashes accumulate on
    the slot, which is what the crash budget blacklists."""

    def __init__(self, slot_id: int):
        self.id = slot_id
        self.proc: Optional[subprocess.Popen] = None
        self.state = _DEAD
        self.incarnation = 0
        self.crashes = 0
        self.tasks_done = 0
        self.last_heartbeat = 0.0
        self.restart_at = 0.0      # monotonic time gating respawn
        self.hang_kill = False     # liveness SIGKILL in flight
        self.cancel_kill = False   # cancel/deadline kill: not a crash
        self.inbox: "queue.Queue" = queue.Queue()
        self.write_lock = threading.Lock()
        self.device_spec: Optional[Dict[str, Any]] = None  # hello frame
        self.cpu_ns = 0            # child CPU (user+sys) across tasks

    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None


class WorkerPool:
    """N supervised long-lived worker processes executing one task each
    at a time.  Thread-safe: run() may be called concurrently from many
    task threads; each call owns one slot for the duration."""

    def __init__(self, count: int = 2, heartbeat_ms: int = 100,
                 liveness_ms: int = 2000, crash_budget: int = 3,
                 restart_backoff_ms: int = 50, drain_ms: int = 1000):
        self.count = max(1, int(count))
        self.heartbeat_ms = max(10, int(heartbeat_ms))
        self.liveness_ms = max(self.heartbeat_ms * 2, int(liveness_ms))
        self.crash_budget = int(crash_budget)  # crashes a slot SURVIVES
        # (0 = blacklist on first crash, negative = never blacklist)
        self.restart_backoff_ms = max(0, int(restart_backoff_ms))
        self.drain_ms = max(0, int(drain_ms))
        self.closed = False
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._slots = [_Slot(i) for i in range(self.count)]
        self._crash_listeners: List[Callable[[int], None]] = []

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "WorkerPool":
        with self._lock:
            for slot in self._slots:
                self._spawn(slot, restart=False)
        return self

    def _spawn(self, slot: _Slot, restart: bool) -> None:
        """Fork a fresh child into `slot` (caller holds the lock).  A
        fresh inbox per incarnation keeps stale sentinels/results from a
        previous process out of the next task's wait loop."""
        from blaze_tpu.bridge import xla_stats
        slot.inbox = queue.Queue()
        slot.incarnation += 1
        slot.hang_kill = False
        slot.cancel_kill = False
        try:
            slot.proc = subprocess.Popen(
                [sys.executable, "-m", "blaze_tpu.parallel.workers",
                 "--child"],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                bufsize=0, env=self._child_env(slot))
        except OSError as e:
            slot.proc = None
            slot.state = _DEAD
            slot.restart_at = time.monotonic() + 1.0
            log.error("worker %d spawn failed: %s", slot.id, e)
            raise
        slot.state = _STARTING
        slot.last_heartbeat = time.monotonic()
        xla_stats.note_worker_spawn(restart=restart)
        t = threading.Thread(
            target=self._reader, args=(slot, slot.proc, slot.inbox),
            name=f"blaze-worker-reader-{slot.id}", daemon=True)
        t.start()

    @staticmethod
    def _child_env(slot: _Slot) -> Optional[Dict[str, str]]:
        """Spawn env for one child; None inherits the parent env as-is.
        With workers.pinDevices each child is pinned to exactly ONE
        emulated device (`JAX_PLATFORMS=cpu`,
        `--xla_force_host_platform_device_count=1`) — the
        process-per-device scaling harness: N workers x 1 device instead
        of 1 process x N virtual devices, so the multichip bench's
        collective overhead is cross-PROCESS, not cross-thread.  Any
        device-count flag inherited from a multichip parent is stripped
        first (the parent emulates N devices; its children must not)."""
        from blaze_tpu import config
        if not config.WORKERS_PIN_DEVICES.get():
            return None
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       env.get("XLA_FLAGS", "")).strip()
        env["XLA_FLAGS"] = (flags +
                            " --xla_force_host_platform_device_count=1"
                            ).strip()
        env["BLAZE_WORKER_DEVICE_SLOT"] = str(slot.id)
        return env

    def _reader(self, slot: _Slot, proc: subprocess.Popen,
                inbox: "queue.Queue") -> None:
        """Per-incarnation reader: hello promotes the slot to idle,
        heartbeats stamp liveness, results go to the inbox, EOF/torn
        frames become the crash sentinel (None)."""
        try:
            while True:
                msg = _recv_msg(proc.stdout)
                if msg is None:
                    break
                kind = msg.get("kind")
                if kind == "hello":
                    with self._cond:
                        if slot.proc is proc and slot.state == _STARTING:
                            slot.state = _IDLE
                            slot.device_spec = msg.get("device_spec")
                            slot.last_heartbeat = time.monotonic()
                            self._cond.notify_all()
                elif kind == "heartbeat":
                    slot.last_heartbeat = time.monotonic()
                    if msg.get("spans"):
                        # mid-task child spans stream back in heartbeat
                        # frames; rebase the child clock onto ours
                        tracing.ingest(msg["spans"], worker=slot.id,
                                       clock_ns=msg.get("mono_ns"))
                else:
                    slot.last_heartbeat = time.monotonic()
                    inbox.put(msg)
        except Exception:
            pass  # torn frame / CRC mismatch == the process is gone
        inbox.put(None)
        with self._cond:
            self._cond.notify_all()

    def add_crash_listener(self, fn: Callable[[int], None]) -> None:
        """`fn(worker_id)` runs (outside the pool lock) after a worker
        death is recorded — the scheduler's map-output invalidation
        hook."""
        with self._lock:
            self._crash_listeners.append(fn)

    def remove_crash_listener(self, fn: Callable[[int], None]) -> None:
        with self._lock:
            try:
                self._crash_listeners.remove(fn)
            except ValueError:
                pass

    def _fire_crash_listeners(self, worker_id: int) -> None:
        with self._lock:
            listeners = list(self._crash_listeners)
        for fn in listeners:
            try:
                fn(worker_id)
            except Exception:
                log.exception("worker crash listener failed")

    # -- supervision -------------------------------------------------------

    def _record_crash(self, slot: _Slot, hang: bool) -> None:
        """Caller holds the lock.  Counts the crash against the slot's
        budget and either schedules a backoff restart or blacklists."""
        from blaze_tpu.bridge import xla_stats
        slot.crashes += 1
        xla_stats.note_worker_crash(hang=hang)
        if self.crash_budget >= 0 and slot.crashes > self.crash_budget:
            slot.state = _BLACKLISTED
            xla_stats.note_worker_blacklisted()
            log.warning("worker %d blacklisted after %d crashes",
                        slot.id, slot.crashes)
        else:
            slot.state = _DEAD
            backoff = (self.restart_backoff_ms / 1e3
                       * (2 ** max(0, slot.crashes - 1)))
            slot.restart_at = time.monotonic() + min(backoff, 10.0)
        slot.proc = None
        self._cond.notify_all()

    def _maintain(self) -> None:
        """Caller holds the lock: reap idle deaths, respawn dead slots
        whose backoff has elapsed."""
        now = time.monotonic()
        for slot in self._slots:
            if slot.state in (_IDLE, _STARTING) and slot.proc is not None \
                    and slot.proc.poll() is not None:
                # died while not running a task (import error, OOM-kill
                # at rest): still a crash for budget purposes
                log.warning("worker %d exited idle (rc=%s)", slot.id,
                            slot.proc.returncode)
                self._record_crash(slot, hang=False)
            if slot.state == _DEAD and not self.closed \
                    and now >= slot.restart_at:
                try:
                    self._spawn(slot, restart=True)
                except OSError:
                    pass

    def _kill(self, slot: _Slot, sig: int) -> None:
        proc = slot.proc
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(sig)
            except (ProcessLookupError, OSError):
                pass

    def _escalate_stop(self, slot: _Slot, task_id: int,
                       grace_s: float = 0.2) -> None:
        """Cancel-message -> SIGTERM -> SIGKILL ladder for a busy slot
        whose task must stop NOW (query cancelled / deadline)."""
        proc = slot.proc
        try:
            if proc is not None and proc.stdin is not None:
                _send_msg(proc.stdin, {"kind": "cancel", "task_id": task_id},
                          slot.write_lock)
        except (OSError, ValueError):
            pass
        deadline = time.monotonic() + grace_s
        while proc is not None and proc.poll() is None \
                and time.monotonic() < deadline:
            # the child may finish the task and go idle within grace; a
            # result frame means we can keep the (healthy) process
            try:
                item = slot.inbox.get(timeout=0.02)
            except queue.Empty:
                continue
            if isinstance(item, dict) and item.get("task_id") == task_id:
                with self._lock:
                    if slot.state == _BUSY:
                        slot.state = _IDLE
                        self._cond.notify_all()
                return
            if item is None:
                break
        self._kill(slot, signal.SIGTERM)
        if proc is not None:
            try:
                proc.wait(timeout=grace_s)
            except subprocess.TimeoutExpired:
                self._kill(slot, signal.SIGKILL)
                try:
                    proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass

    # -- dispatch ----------------------------------------------------------

    def _acquire(self, exclude: Set[int], deadline: Optional[float],
                 query=None) -> _Slot:
        with self._cond:
            dropped_exclude = False
            while True:
                if self.closed:
                    raise WorkerPoolUnavailable("worker pool is shut down")
                if query is not None and query.cancelled:
                    query.check()
                self._maintain()
                viable = [s for s in self._slots
                          if s.state != _BLACKLISTED]
                if not viable:
                    raise WorkerPoolUnavailable(
                        "all workers blacklisted by the crash budget")
                eligible = [s for s in viable if s.state == _IDLE
                            and s.id not in exclude]
                if not eligible and not dropped_exclude \
                        and all(s.id in exclude for s in viable):
                    # the retry excluded every surviving worker; running
                    # SOMEWHERE beats not running at all
                    dropped_exclude = True
                    exclude = set()
                    continue
                if eligible:
                    slot = eligible[0]
                    slot.state = _BUSY
                    slot.last_heartbeat = time.monotonic()
                    return slot
                if deadline is not None \
                        and time.monotonic() >= deadline:
                    raise TimeoutError(
                        "worker pool: no idle worker before deadline")
                self._cond.wait(timeout=0.05)

    def _release(self, slot: _Slot) -> None:
        with self._cond:
            if slot.state == _BUSY:
                slot.state = _IDLE
            self._cond.notify_all()

    def _directive(self, what: str) -> Dict[str, int]:
        """Evaluate worker-* fault sites PARENT-side at dispatch so
        chaos decisions stay deterministic in (seed, site, occurrence)
        regardless of child process identity, then ship the directive
        for the child to act out."""
        d: Dict[str, int] = {}
        if faults.fires("worker-crash", what=what):
            d["kill_after_ms"] = 15
        if faults.fires("worker-hang", what=what):
            d["hang_ms"] = self.liveness_ms * 10
        if faults.fires("worker-slow", what=what):
            from blaze_tpu import config
            d["delay_ms"] = max(0, config.FAULTS_WORKER_SLOW_MS.get())
        return d

    def run(self, spec: Dict[str, Any], exclude: Optional[Set[int]] = None,
            timeout_s: Optional[float] = None, query=None,
            what: str = "task", cancel_event=None,
            on_assign=None) -> Any:
        """Execute `spec` ({"fn": "module:qualname", "args": tuple}) on
        one worker and return its result.  Raises WorkerCrashed (with
        the dead worker's id) on crash/hang, TimeoutError past
        `timeout_s`, the reconstructed task error otherwise.

        `cancel_event` is the speculative-attempt token: when set (a
        sibling attempt committed first) the in-flight task is cancelled
        like a deadline — stop escalation, no crash-budget charge — and
        TaskKilledError is raised so the caller's retry loop treats the
        attempt as dead rather than retryable.  `on_assign(worker_id)`
        fires once the task is dispatched, letting the wave loop steer a
        later duplicate attempt away from this worker."""
        from blaze_tpu import config
        from blaze_tpu.bridge import xla_stats
        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)
        slot = self._acquire(set(exclude or ()), deadline, query)
        if cancel_event is not None and cancel_event.is_set():
            # the sibling won while this attempt queued for a slot:
            # hand the slot straight back instead of dispatching a
            # task whose output is already dead
            self._release(slot)
            from blaze_tpu.bridge.context import TaskKilledError
            raise TaskKilledError(
                f"{what}: attempt cancelled before dispatch — a "
                f"sibling attempt committed first")
        if on_assign is not None:
            on_assign(slot.id)
        incarnation = slot.incarnation
        inbox = slot.inbox
        proc = slot.proc
        task_id = slot.tasks_done + slot.crashes + incarnation * 100003
        msg = {"kind": "task", "task_id": task_id,
               "fn": spec["fn"], "args": tuple(spec.get("args") or ()),
               "conf": config.conf.snapshot(),
               "directive": self._directive(what),
               "heartbeat_ms": self.heartbeat_ms}
        trace = tracing.wire_context(worker=slot.id)
        if trace is not None:
            # trace context rides the framed wire protocol; absent
            # entirely when tracing is off (zero disabled-path bytes)
            msg["trace"] = trace
        try:
            _send_msg(proc.stdin, msg, slot.write_lock)
        except (OSError, ValueError) as e:
            return self._handle_crash(slot, incarnation, hang=False,
                                      reason=f"dispatch failed: {e}")
        xla_stats.note_worker_task()
        slot.last_heartbeat = time.monotonic()
        liveness_s = self.liveness_ms / 1e3
        while True:
            try:
                item = inbox.get(timeout=0.05)
            except queue.Empty:
                item = _PENDING
            if item is None:
                hang = slot.hang_kill
                return self._handle_crash(slot, incarnation, hang=hang,
                                          reason="heartbeat miss: liveness "
                                                 "deadline exceeded"
                                          if hang else "")
            if item is not _PENDING and isinstance(item, dict):
                if item.get("task_id") != task_id:
                    continue  # stale result from a cancelled attempt
                return self._finish(slot, item)
            now = time.monotonic()
            if query is not None and query.cancelled:
                self._cancel_slot(slot, task_id)
                query.check()
            if cancel_event is not None and cancel_event.is_set():
                # sibling attempt won the first-wins commit: ABANDON the
                # attempt rather than killing the child.  The loser runs
                # to completion in the worker (its late commit is
                # rejected by the attempt arbitration on every shuffle
                # tier) and the process keeps its warm backend + compile
                # caches — killing it would make the next task on this
                # slot pay a cold re-init costlier than the straggle
                # being hedged.  No crash-budget charge.
                self._abandon_slot(slot, task_id, incarnation)
                from blaze_tpu.bridge.context import TaskKilledError
                raise TaskKilledError(
                    f"{what}: worker {slot.id} attempt cancelled — a "
                    f"sibling attempt committed first")
            if deadline is not None and now >= deadline:
                self._cancel_slot(slot, task_id)
                raise TimeoutError(
                    f"{what}: worker {slot.id} task exceeded "
                    f"{timeout_s:g}s deadline")
            if now - slot.last_heartbeat > liveness_s:
                # busy and silent past the liveness deadline: hung.
                # SIGKILL; the reader's EOF sentinel completes the story.
                with self._lock:
                    slot.hang_kill = True
                log.warning("worker %d (pid %s) missed heartbeats for "
                            "%.2fs; killing", slot.id, slot.pid(),
                            now - slot.last_heartbeat)
                self._kill(slot, signal.SIGKILL)

    def _abandon_slot(self, slot: _Slot, task_id: int,
                      incarnation: int) -> None:
        """Detach from a speculative loser WITHOUT stopping the child:
        a drainer thread babysits the slot until the task's result
        frame arrives (discarded — first-wins already settled), then
        releases it.  The slot stays _BUSY meanwhile so `_acquire`
        cannot double-book the worker.  Liveness is still enforced: a
        child that stops heartbeating mid-abandon is killed and takes
        the normal crash path (with budget charge — it really died)."""
        from blaze_tpu.bridge import xla_stats
        xla_stats.note_worker_cancel()
        tracing.instant("worker_cancel_escalation", worker=slot.id,
                        action="abandon")
        liveness_s = self.liveness_ms / 1e3

        def drain() -> None:
            while True:
                if self.closed:
                    return
                try:
                    item = slot.inbox.get(timeout=0.05)
                except queue.Empty:
                    item = _PENDING
                if item is None:
                    try:
                        self._handle_crash(slot, incarnation, hang=slot.
                                           hang_kill)
                    except BaseException:
                        pass
                    return
                if item is not _PENDING and isinstance(item, dict):
                    if item.get("task_id") != task_id:
                        continue
                    try:
                        self._finish(slot, item)
                    except BaseException:
                        pass  # the loser's result (or error) is dead
                    return
                if time.monotonic() - slot.last_heartbeat > liveness_s:
                    with self._lock:
                        slot.hang_kill = True
                    log.warning("worker %d (pid %s) missed heartbeats "
                                "while draining an abandoned attempt; "
                                "killing", slot.id, slot.pid())
                    self._kill(slot, signal.SIGKILL)

        threading.Thread(target=drain, daemon=True,
                         name=f"blaze-worker-{slot.id}-abandon").start()

    def _cancel_slot(self, slot: _Slot, task_id: int) -> None:
        """Deadline/cancel escalation.  If the process survived (it
        finished the task inside the grace window) it stays; otherwise
        it restarts WITHOUT a crash-budget charge."""
        from blaze_tpu.bridge import xla_stats
        with self._lock:
            slot.cancel_kill = True
        xla_stats.note_worker_cancel()
        tracing.instant("worker_cancel_escalation", worker=slot.id,
                        action="cancel")
        self._escalate_stop(slot, task_id)
        with self._cond:
            proc = slot.proc
            if proc is not None and proc.poll() is not None:
                slot.state = _DEAD
                slot.proc = None
                slot.restart_at = time.monotonic()
            elif slot.state == _BUSY:
                slot.state = _IDLE
            slot.cancel_kill = False
            self._cond.notify_all()

    def _handle_crash(self, slot: _Slot, incarnation: int, hang: bool,
                      reason: str = "") -> Any:
        rc = None
        with self._cond:
            proc = slot.proc
            if proc is not None:
                try:
                    rc = proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    rc = None
            if slot.incarnation == incarnation \
                    and slot.state not in (_DEAD, _BLACKLISTED):
                self._record_crash(slot, hang=hang)
        self._fire_crash_listeners(slot.id)
        if rc is not None and rc < 0 and not reason:
            reason = f"killed by signal {-rc}"
        raise WorkerCrashed(worker_id=slot.id, exit_code=rc, reason=reason)

    def _finish(self, slot: _Slot, res: Dict[str, Any]) -> Any:
        if res.get("spans"):
            # final child spans ride the result frame — including an
            # abandoned speculation loser's (the drainer lands here too)
            tracing.ingest(res["spans"], worker=slot.id,
                           clock_ns=res.get("mono_ns"))
        cpu_ns = res.get("cpu_ns")
        if cpu_ns:
            # actual worker-process CPU (user+sys from os.times in the
            # child) — the multichip bench derives host_core_limited
            # from the SUM of these vs wall, not from a host heuristic
            from blaze_tpu.bridge import xla_stats
            xla_stats.note_worker_cpu(int(cpu_ns))
        with self._cond:
            slot.tasks_done += 1
            if cpu_ns:
                slot.cpu_ns += int(cpu_ns)
            if slot.state == _BUSY:
                slot.state = _IDLE
            self._cond.notify_all()
        if res.get("ok"):
            value = res.get("value")
            if isinstance(value, dict):
                value["_worker_id"] = slot.id
            return value
        fetch = res.get("fetch")
        if fetch:
            raise FetchFailedError(fetch[0], fetch[1],
                                   res.get("error_msg", ""))
        raise RemoteTaskError(
            f"worker {slot.id}: {res.get('error_type', 'Exception')}: "
            f"{res.get('error_msg', '')}",
            remote_classify=res.get("classify", "fatal"))

    # -- shutdown / health -------------------------------------------------

    def shutdown(self, wait: bool = True) -> None:
        with self._cond:
            self.closed = True
            slots = list(self._slots)
            self._cond.notify_all()
        if wait:
            for slot in slots:
                proc = slot.proc
                if proc is None or proc.poll() is not None:
                    continue
                try:
                    _send_msg(proc.stdin, {"kind": "shutdown"},
                              slot.write_lock)
                except (OSError, ValueError):
                    pass
            deadline = time.monotonic() + self.drain_ms / 1e3
            for slot in slots:
                proc = slot.proc
                if proc is None:
                    continue
                remaining = max(0.0, deadline - time.monotonic())
                try:
                    proc.wait(timeout=remaining)
                except subprocess.TimeoutExpired:
                    pass
        for slot in slots:
            proc = slot.proc
            if proc is not None and proc.poll() is None:
                self._kill(slot, signal.SIGTERM)
        for slot in slots:
            proc = slot.proc
            if proc is None:
                continue
            try:
                proc.wait(timeout=0.2)
            except subprocess.TimeoutExpired:
                self._kill(slot, signal.SIGKILL)
                try:
                    proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass
            slot.proc = None
            slot.state = _DEAD

    def health(self) -> List[Dict[str, Any]]:
        with self._lock:
            self._maintain()
            now = time.monotonic()
            return [{"worker": s.id, "pid": s.pid(), "state": s.state,
                     "crashes": s.crashes, "tasks_done": s.tasks_done,
                     "incarnation": s.incarnation,
                     "device_spec": s.device_spec,
                     "cpu_s": s.cpu_ns / 1e9,
                     "heartbeat_age_ms": int((now - s.last_heartbeat) * 1e3)
                     if s.state == _BUSY else None}
                    for s in self._slots]


_PENDING = object()


# ---------------------------------------------------------------------------
# Module-level pool registry (config-driven singleton)

_pool: Optional[WorkerPool] = None
_pool_lock = threading.Lock()
_pool_failed = False


def get_pool() -> Optional[WorkerPool]:
    """The config-driven pool singleton: created lazily from the
    `auron.tpu.workers.*` knobs at first use, None when disabled or
    unspawnable (callers then take the in-process thread path)."""
    global _pool, _pool_failed
    from blaze_tpu import config
    if not (config.WORKERS_ENABLE.get()
            or config.SERVING_USE_WORKERS.get()):
        return None
    with _pool_lock:
        if _pool is not None and not _pool.closed:
            return _pool
        if _pool_failed:
            return None
        try:
            _pool = WorkerPool(
                count=config.WORKERS_COUNT.get(),
                heartbeat_ms=config.WORKERS_HEARTBEAT_MS.get(),
                liveness_ms=config.WORKERS_LIVENESS_MS.get(),
                crash_budget=config.WORKERS_CRASH_BUDGET.get(),
                restart_backoff_ms=config.WORKERS_RESTART_BACKOFF_MS.get(),
                drain_ms=config.WORKERS_DRAIN_MS.get()).start()
        except Exception:
            log.exception("worker pool spawn failed; falling back to "
                          "in-process threads")
            _pool = None
            _pool_failed = True
            return None
        return _pool


def active_pool() -> Optional[WorkerPool]:
    """The live pool if one exists — never creates."""
    with _pool_lock:
        if _pool is not None and not _pool.closed:
            return _pool
        return None


def shutdown_pool(wait: bool = True) -> None:
    """Close and forget the singleton (tests/bench re-knob between
    legs; serving shutdown)."""
    global _pool, _pool_failed
    with _pool_lock:
        pool, _pool = _pool, None
        _pool_failed = False
    if pool is not None:
        pool.shutdown(wait=wait)


def pool_health() -> Dict[str, Any]:
    """JSON-ready pool health for the /serving endpoint."""
    from blaze_tpu import config
    from blaze_tpu.bridge import xla_stats
    pool = active_pool()
    out: Dict[str, Any] = {"enabled": bool(config.WORKERS_ENABLE.get()),
                           "active": pool is not None}
    if pool is not None:
        out["slots"] = pool.health()
    out["counters"] = xla_stats.worker_stats()
    return out


# ---------------------------------------------------------------------------
# Task entry points (must be module-level: specs cross the process
# boundary as "module:qualname" strings, not closures)

def run_shuffle_map_task(task: dict) -> dict:
    """Execute one shuffle-writer TaskDefinition inside a worker: the
    native runtime writes the map output files (tmp + os.replace commit,
    so a SIGKILL mid-write leaves NOTHING committed) and the metric tree
    rides the result frame home for the parent scheduler to absorb.

    `task["shuffle_inputs"]` is the shipped map-output table: on-disk
    segment lists for every upstream stage:// resource the per-task
    plan reads (resolved by the parent at dispatch).  They're
    registered in THIS process's resource map for the duration of the
    task and removed after — the worker is long-lived and must not
    accumulate stale block lists across tasks."""
    from blaze_tpu.bridge.resource import get_resource, put_resource
    from blaze_tpu.bridge.runtime import NativeExecutionRuntime
    from blaze_tpu.plan.proto_serde import task_definition_to_bytes
    from blaze_tpu.shuffle.reader import FileSegmentBlock
    task = dict(task)
    shuffle_inputs = task.pop("shuffle_inputs", None) or {}
    rids = []
    try:
        for rid, parts in shuffle_inputs.items():
            blocks = [[FileSegmentBlock(data, off, length,
                                        stage_id=sid, map_id=mid)
                       for (data, off, length, sid, mid) in segs]
                      for segs in parts]

            def blocks_for(p, _b=blocks):
                return iter(_b[p]) if 0 <= p < len(_b) else iter(())
            put_resource(rid, blocks_for)
            rids.append(rid)
        td = task_definition_to_bytes(task)
        rt = NativeExecutionRuntime(td).start()
        try:
            for _ in rt.batches():
                pass
        finally:
            tree = rt.finalize()
        return {"metrics": tree.to_dict()}
    finally:
        for rid in rids:
            get_resource(rid, remove=True)


def _task_echo(*args) -> dict:
    """Test/bench helper: round-trips its args."""
    return {"echo": list(args), "pid": os.getpid()}


def _task_sleep(seconds: float, value: Any = None) -> dict:
    """Test/bench helper: hold a worker busy (heartbeating) then echo."""
    time.sleep(float(seconds))
    return {"value": value, "pid": os.getpid()}


def _task_raise(kind: str = "runtime") -> None:
    """Test/bench helper: raise a classified error inside the worker."""
    if kind == "fetch":
        raise FetchFailedError(7, 3, "injected remote fetch failure")
    if kind == "retryable":
        raise ConnectionError("injected transient failure")
    raise RuntimeError("injected fatal failure")


def _task_device_shard(rows: int, groups: int, reps: int = 1,
                       seed: int = 0) -> dict:
    """Bench helper (bench.py --multichip): one process-per-device shard
    of the grouped-agg microbench.  jax initializes INSIDE this pinned
    child, seeing exactly the one emulated device the spawn env granted,
    so the N-shard wave measures real cross-process scaling rather than
    N virtual devices time-slicing one interpreter.  Reports wall AND
    process CPU (user+sys) so the supervisor can compute
    cpu_parallelism = sum(cpu_s) / wall across the wave — the honest
    host_core_limited signal."""
    t_wall = time.perf_counter()
    cpu0 = os.times()
    import jax
    import jax.numpy as jnp
    import numpy as np
    rows, groups = int(rows), int(groups)
    rng = np.random.default_rng(int(seed))
    keys = jnp.asarray(rng.integers(0, groups, size=rows, dtype=np.int64))
    vals = jnp.asarray(rng.random(rows))

    @jax.jit
    def agg(k, v):
        return jax.ops.segment_sum(v, k, num_segments=groups)

    out = None
    for _ in range(max(1, int(reps))):
        out = agg(keys, vals)
    out.block_until_ready()
    cpu1 = os.times()
    return {"wall_s": time.perf_counter() - t_wall,
            "cpu_s": ((cpu1.user - cpu0.user) +
                      (cpu1.system - cpu0.system)),
            "checksum": float(jnp.sum(out)),
            "devices": jax.device_count(),
            "platform": jax.default_backend(),
            "pid": os.getpid()}


# ---------------------------------------------------------------------------
# Child side

def _child_device_spec() -> Optional[Dict[str, Any]]:
    """Describe the device this child was pinned to, from the spawn env
    ALONE — importing jax in the frame loop would initialize a backend
    the first task's conf snapshot has not configured yet.  None when
    the pool spawned without pinning (the default)."""
    slot = os.environ.get("BLAZE_WORKER_DEVICE_SLOT")
    if slot is None:
        return None
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)",
                  os.environ.get("XLA_FLAGS", ""))
    return {"slot": int(slot),
            "platform": os.environ.get("JAX_PLATFORMS") or "default",
            "local_devices": int(m.group(1)) if m else None}


def _resolve_fn(spec: str) -> Callable:
    mod_name, _, qual = spec.partition(":")
    fn: Any = importlib.import_module(mod_name)
    for part in qual.split("."):
        fn = getattr(fn, part)
    return fn


def _run_child_task(msg: Dict[str, Any], out, out_lock) -> Dict[str, Any]:
    from blaze_tpu import config
    config.conf.replace(msg.get("conf") or {})
    directive = msg.get("directive") or {}
    hb_s = max(10, int(msg.get("heartbeat_ms") or 100)) / 1e3
    kill_timer = None
    if directive.get("kill_after_ms") is not None:
        # worker-crash: really die, mid-task, the hard way
        kill_timer = threading.Timer(
            directive["kill_after_ms"] / 1e3,
            lambda: os.kill(os.getpid(), signal.SIGKILL))
        kill_timer.daemon = True
        kill_timer.start()
    hang_ms = directive.get("hang_ms")
    if hang_ms is not None:
        # worker-hang: wedge WITHOUT heartbeats so the parent's liveness
        # deadline — not this sleep expiring — is what ends us
        time.sleep(hang_ms / 1e3)
    stop_beat = threading.Event()
    trace = msg.get("trace")

    def _beat() -> None:
        while not stop_beat.wait(hb_s):
            beat: Dict[str, Any] = {"kind": "heartbeat"}
            if trace:
                tracing.instant("worker_heartbeat", pid=os.getpid())
                beat["spans"] = tracing.take_buffered()
                beat["mono_ns"] = time.perf_counter_ns()
            try:
                _send_msg(out, beat, out_lock)
            except Exception:
                return

    beater = None
    if hang_ms is None:
        beater = threading.Thread(target=_beat, name="blaze-worker-beat",
                                  daemon=True)
        beater.start()
    cpu0 = os.times()

    def _cpu_ns() -> int:
        t = os.times()
        return int(((t.user - cpu0.user) +
                    (t.system - cpu0.system)) * 1e9)

    try:
        if directive.get("delay_ms"):
            # worker-slow: stall but KEEP heartbeating — slow must never
            # be mistaken for dead
            time.sleep(directive["delay_ms"] / 1e3)
        fn = _resolve_fn(msg["fn"])
        if trace:
            # adopt the parent trace context: spans emitted while the
            # task runs buffer locally and ship home in heartbeat
            # frames (above) and in this result frame
            with tracing.remote_task_scope(trace), \
                    tracing.span("worker_task", pid=os.getpid(),
                                 fn=msg["fn"]):
                value = fn(*msg.get("args", ()))
        else:
            value = fn(*msg.get("args", ()))
        if kill_timer is not None:
            # the task won the race with the kill timer: worker-crash
            # means this process DIES.  Committed output files may
            # exist but the result frame is lost — the exact
            # lost-executor shape the parent's map-output re-validation
            # and retry-on-another-worker handle
            os.kill(os.getpid(), signal.SIGKILL)
        reply = {"kind": "result", "task_id": msg["task_id"], "ok": True,
                 "value": value, "cpu_ns": _cpu_ns()}
        if trace:
            reply["spans"] = tracing.take_buffered()
            reply["mono_ns"] = time.perf_counter_ns()
        return reply
    except BaseException as e:
        if kill_timer is not None:
            os.kill(os.getpid(), signal.SIGKILL)
        fetch = None
        if isinstance(e, FetchFailedError):
            fetch = (e.stage_id, e.map_id)
        reply = {"kind": "result", "task_id": msg["task_id"], "ok": False,
                 "error_type": type(e).__name__, "error_msg": str(e),
                 "classify": classify_exception(e), "fetch": fetch,
                 "cpu_ns": _cpu_ns()}
        if trace:
            reply["spans"] = tracing.take_buffered()
            reply["mono_ns"] = time.perf_counter_ns()
        return reply
    finally:
        stop_beat.set()
        if beater is not None:
            beater.join(timeout=1.0)


def child_main() -> int:
    """Worker process entry (`--child`): frame loop over binary stdio.
    stdout is reserved for protocol frames — anything the task prints is
    rerouted to stderr so it cannot corrupt the stream."""
    inp = sys.stdin.buffer
    out = sys.stdout.buffer
    sys.stdout = sys.stderr
    out_lock = threading.Lock()
    signal.signal(signal.SIGTERM, lambda *_: os._exit(143))
    hello: Dict[str, Any] = {"kind": "hello", "pid": os.getpid()}
    spec = _child_device_spec()
    if spec is not None:
        hello["device_spec"] = spec
    _send_msg(out, hello, out_lock)
    while True:
        try:
            msg = _recv_msg(inp)
        except Exception:
            return 1
        if msg is None or msg.get("kind") == "shutdown":
            return 0
        if msg.get("kind") != "task":
            continue  # stray cancel for a task we already finished
        reply = _run_child_task(msg, out, out_lock)
        try:
            _send_msg(out, reply, out_lock)
        except Exception:
            return 1


if __name__ == "__main__":
    if "--child" in sys.argv:
        sys.exit(child_main())
    print("usage: python -m blaze_tpu.parallel.workers --child",
          file=sys.stderr)
    sys.exit(2)
