"""Continuous micro-batch streaming over the staged scheduler.

The last pillar of the reference (PAPER.md: auron-flink-extension/ —
FlinkAuronCalcOperator + AuronKafkaSourceFunction own ONE long-lived
native plan per Flink task): a converted Flink pipeline
(Kafka source -> event-time windowed aggregation -> sink) runs as a
long-lived query instead of the caller-pumped one-shot loop in
convert/flink_runtime.py.  Flare (PAPERS.md) motivates the shape: keep
the compiled plan resident across batches — the StreamExecutor reuses
ONE DagScheduler for every epoch, so PR 8's StageProgram fingerprint
cache serves the same fused pipeline from warm state epoch after epoch.

Epoch anatomy (each one a bounded batch job with streaming book-ends):

  1. ``stream-epoch`` fault point + QueryContext.check() — cancellation,
     deadline and injected chaos all tear down at an epoch boundary.
  2. Poll each source partition from the committed offsets; stage the
     records behind the plan's kafka poll resource.
  3. Run the converted plan through DagScheduler.run_collect (full wire
     path: TaskDefinition protos, stage split, lineage recovery).
  4. Fold the output into EventTimeWindowState; advance the watermark
     from the polled record timestamps; fire due panes.
  5. Write the fired panes as a sink ATTEMPT, then commit the epoch
     manifest (offsets + watermark + window state + attempt ref)
     first-wins via CheckpointManager.  Commit wins -> promote the
     attempt; commit loses (we are a replay) -> discard it and adopt
     the committed manifest's state.  Exactly-once either way.

Recovery: any retryable failure restores offsets/watermark/window state
from the latest committed manifest (repairing a committed-but-
unpromoted sink attempt) and re-runs the in-flight epoch, bounded by
``auron.tpu.stream.maxRecoveries``.
"""

from __future__ import annotations

import copy
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import pyarrow as pa

from blaze_tpu import config, faults
from blaze_tpu.ops.kafka import KafkaRecord
from blaze_tpu.ops.window import (EventTimeWindowSpec, EventTimeWindowState,
                                  WatermarkTracker)
from blaze_tpu.streaming.checkpoint import CheckpointManager
from blaze_tpu.streaming.sink import ExactlyOnceParquetSink

_RETRYABLE = (faults.InjectedFault, faults.FetchFailedError,
              faults.ShuffleChecksumError)


@dataclass
class StreamWindowConfig:
    """The windowed-aggregation half of a streaming query: which column
    is event time, how rows are keyed, and which aggregates each pane
    carries.  `ts_field` is appended to the scan output by the kafka
    scan's event_time_field (record timestamps -> int64 epoch ms)."""

    spec: EventTimeWindowSpec
    ts_field: str = "__event_time"
    keys: List[str] = field(default_factory=list)
    aggs: List[Tuple[str, Optional[str]]] = field(
        default_factory=lambda: [("count", None)])


class MemoryStreamSource:
    """Bounded in-memory Kafka (the broker-less test/bench source): one
    record list per partition, polled by offset.  ``poll`` returns None
    once a partition is drained — end-of-stream for the executor."""

    def __init__(self, partitions: Sequence[Sequence[KafkaRecord]]):
        self._parts = [sorted(p, key=lambda r: r.offset)
                       for p in partitions]

    @property
    def num_partitions(self) -> int:
        return len(self._parts)

    def poll(self, partition: int, offset: int,
             max_records: int) -> Optional[List[KafkaRecord]]:
        recs = [r for r in self._parts[partition] if r.offset >= offset]
        if not recs:
            return None
        return recs[:max_records]

    def lag(self, offsets: Dict[int, int]) -> int:
        return sum(len([r for r in p if r.offset >= offsets.get(i, 0)])
                   for i, p in enumerate(self._parts))


def _ensure_event_time(ir: Dict[str, Any], ts_field: str) -> None:
    """Thread the scan's event-time column through the converted plan:
    set event_time_field on the kafka_scan and re-project it through
    every calc node above, so the window operator sees it at the top.
    Converted Flink chains are linear filter/project stacks; anything
    else can't carry a per-row timestamp and is rejected."""
    chain: List[Dict[str, Any]] = []
    node = ir
    while node.get("kind") != "kafka_scan":
        if node.get("kind") not in ("project", "filter"):
            raise ValueError(
                f"event-time streaming supports kafka_scan + calc "
                f"chains; found {node.get('kind')!r}")
        chain.append(node)
        node = node["input"]
    scan = node
    scan["event_time_field"] = ts_field
    ts_index = len(scan["schema"]["fields"])  # appended after deser cols
    for n in reversed(chain):
        if n["kind"] == "filter":
            continue  # filters pass all columns through
        n["exprs"].append({"kind": "column", "index": ts_index})
        n.setdefault("names", [f"f{i}" for i in
                               range(len(n["exprs"]) - 1)])
        n["names"].append(ts_field)
        ts_index = len(n["exprs"]) - 1


class StreamExecutor:
    """One long-lived streaming query: epochs until the source drains
    (bounded sources) or ``max_epochs`` (unbounded)."""

    def __init__(self, plan: Dict[str, Any], source: Any,
                 window: StreamWindowConfig, *,
                 sink_dir: str,
                 checkpoint_dir: Optional[str] = None,
                 ctx: Any = None,
                 num_partitions: Optional[int] = None,
                 max_records_per_poll: Optional[int] = None,
                 scheduler: Any = None):
        from blaze_tpu.plan.planner import create_plan
        from blaze_tpu.plan.stages import DagScheduler

        self._ir = copy.deepcopy(plan)
        scan = self._find_scan(self._ir)
        if scan is None:
            raise ValueError("streaming plan has no kafka_scan source")
        scan.pop("mock_data_json_array", None)  # executor feeds the poll
        # the source's real partition count wins over the scan's default
        # of 1 — otherwise a multi-partition source would silently be
        # polled on partition 0 only and declare end-of-stream early
        src_n = getattr(source, "num_partitions", None)
        self._n = int(num_partitions or src_n
                      or scan.get("num_partitions", 1) or 1)
        if src_n is not None and int(src_n) != self._n:
            raise ValueError(
                f"num_partitions={self._n} disagrees with "
                f"source.num_partitions={src_n}")
        scan["num_partitions"] = self._n
        _ensure_event_time(self._ir, window.ts_field)
        self._resource_id = (f"kafka://"
                             f"{scan.get('operator_id') or scan.get('topic')}")
        self._plan_schema = create_plan(self._ir).schema.to_arrow()

        self.window = window
        self.source = source
        self._max_poll = int(max_records_per_poll
                             or config.BATCH_SIZE.get())
        self._ctx = ctx
        ckpt_dir = (checkpoint_dir or config.STREAM_CHECKPOINT_DIR.get()
                    or None)
        if ckpt_dir is None:
            import tempfile
            ckpt_dir = tempfile.mkdtemp(prefix="blaze-stream-ckpt-")
        self._ckpt = CheckpointManager(ckpt_dir)
        self.sink = ExactlyOnceParquetSink(sink_dir)
        self._sched = scheduler or DagScheduler(query_ctx=ctx)

        self._tracker = WatermarkTracker(
            config.STREAM_WATERMARK_LATENESS_MS.get())
        self._state = EventTimeWindowState(
            window.spec, self._plan_schema, window.ts_field,
            window.keys, window.aggs,
            late_policy=config.STREAM_LATE_SIDE_POLICY.get())
        if ctx is not None:
            self._state.query = ctx  # per-query memory quota on state
        self._offsets: Dict[int, int] = {p: 0 for p in range(self._n)}
        self._epoch = 0
        self.epochs_committed = 0
        self.rows_emitted = 0
        self.records_consumed = 0
        self.late_side: List[dict] = []
        self.epoch_walls_ns: List[int] = []
        self.recovery_walls_ns: List[int] = []

    @staticmethod
    def _find_scan(node: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        if node.get("kind") == "kafka_scan":
            return node
        for key in ("input", "left", "right"):
            child = node.get(key)
            if isinstance(child, dict):
                found = StreamExecutor._find_scan(child)
                if found is not None:
                    return found
        return None

    @classmethod
    def from_flink_plan(cls, plan_json: dict, source: Any,
                        window: StreamWindowConfig,
                        num_partitions: Optional[int] = None,
                        **kw) -> "StreamExecutor":
        from blaze_tpu.convert.flink import convert_flink_plan
        n = int(num_partitions
                or getattr(source, "num_partitions", None) or 1)
        ir = convert_flink_plan(plan_json, num_partitions=n)
        return cls(ir, source, window, num_partitions=n, **kw)

    # -- one epoch -------------------------------------------------------
    def _run_plan(self, polled: Dict[int, List[KafkaRecord]]) -> pa.Table:
        from blaze_tpu.bridge.resource import put_resource, remove_resource

        staged = {p: list(recs) for p, recs in polled.items()}

        def poll(partition: int, max_records: int):
            batch = staged.get(partition, [])[:max_records]
            staged[partition] = staged.get(partition, [])[len(batch):]
            return batch if batch else None

        put_resource(self._resource_id, poll)
        try:
            return self._sched.run_collect(self._ir)
        finally:
            remove_resource(self._resource_id)

    def _restore_from(self, manifest: dict) -> None:
        self._offsets = CheckpointManager.offsets_from(manifest)
        self._tracker.restore(manifest.get("watermark") or {})
        self._state.restore(manifest.get("window") or {})

    def _recover(self) -> None:
        from blaze_tpu.bridge import xla_stats
        t0 = time.perf_counter_ns()
        latest = self._ckpt.latest()
        if latest is None:
            self._offsets = {p: 0 for p in range(self._n)}
            self._tracker.restore({})
            self._state.restore({})
            resume = 0
        else:
            e, manifest = latest
            self._restore_from(manifest)
            self.sink.repair(e, (manifest.get("sink") or {}).get("attempt"))
            resume = e + 1
        replayed = max(0, self._epoch - resume) + 1  # the in-flight epoch
        self._epoch = resume
        self.recovery_walls_ns.append(time.perf_counter_ns() - t0)
        xla_stats.note_stream_recovery(replayed_epochs=replayed)
        from blaze_tpu.bridge import history, tracing
        tracing.instant("stream_recovery", resume_epoch=resume,
                        replayed_epochs=replayed,
                        query=getattr(self._ctx, "query_id", None))
        history.note_stream_recovery(
            getattr(self._ctx, "query_id", None),
            resume_epoch=resume, replayed=replayed)

    def _run_epoch(self) -> bool:
        """Execute + commit one epoch; returns True at end-of-stream."""
        from blaze_tpu.bridge import tracing, xla_stats
        qid = getattr(self._ctx, "query_id", None)
        with tracing.execution_context(query=qid), \
                tracing.span("stream_epoch", epoch=self._epoch, query=qid):
            return self._run_epoch_traced()

    def _run_epoch_traced(self) -> bool:
        from blaze_tpu.bridge import xla_stats

        t0 = time.perf_counter_ns()
        if self._ctx is not None:
            self._ctx.check()
        faults.maybe_fail("stream-epoch", epoch=self._epoch)

        polled: Dict[int, List[KafkaRecord]] = {}
        exhausted = True
        nrecs = 0
        for p in range(self._n):
            recs = self.source.poll(p, self._offsets.get(p, 0),
                                    self._max_poll)
            if recs is None:
                polled[p] = []
            else:
                exhausted = False
                polled[p] = list(recs)
                nrecs += len(recs)

        wm_before = self._tracker.watermark()
        if nrecs:
            table = self._run_plan(polled)
            for p, recs in polled.items():
                for r in recs:
                    self._tracker.observe(p, r.timestamp_ms)
            late = 0
            for rb in table.to_batches():
                late += self._state.add_batch(rb, watermark=wm_before)
            side = self._state.take_late()
            self.late_side.extend(side)
            if late:
                xla_stats.note_stream_late(late, side_rows=len(side))

        final = exhausted
        wm = self._tracker.watermark()
        emitted = self._state.flush() if final else self._state.advance(wm)

        attempt = self.sink.write_attempt(self._epoch, emitted)
        new_offsets = dict(self._offsets)
        for p, recs in polled.items():
            if recs:
                new_offsets[p] = max(new_offsets.get(p, 0),
                                     max(r.offset for r in recs) + 1)
        manifest = {
            "offsets": {str(p): o for p, o in new_offsets.items()},
            "watermark": self._tracker.snapshot(),
            "window": self._state.snapshot(),
            "sink": {"attempt": attempt, "rows": emitted.num_rows},
            "final": final,
        }
        committed = self._ckpt.commit(self._epoch, manifest)
        if committed:
            self.sink.promote(self._epoch, attempt)
            self._offsets = new_offsets
            self.rows_emitted += emitted.num_rows
            self.records_consumed += nrecs
            xla_stats.note_stream_sink(committed=1)
        else:
            # we are a replay of an epoch that already committed: its
            # manifest is the truth — drop our attempt, adopt its state
            self.sink.discard(attempt)
            committed = self._ckpt.load(self._epoch)
            self.sink.repair(self._epoch,
                             (committed.get("sink") or {}).get("attempt"))
            self._restore_from(committed)
            final = bool(committed.get("final"))
            xla_stats.note_stream_sink(dup_skips=1)

        wall = time.perf_counter_ns() - t0
        self.epoch_walls_ns.append(wall)
        self.epochs_committed += 1
        xla_stats.note_stream_epoch(wall, rows=emitted.num_rows,
                                    records=nrecs)
        from blaze_tpu.bridge import history
        history.note_stream_epoch(
            getattr(self._ctx, "query_id", None), epoch=self._epoch,
            rows=emitted.num_rows, records=nrecs, wall_ns=wall,
            committed=committed)
        max_seen = max((t for t in
                        self._tracker.snapshot()["max_ts"].values()),
                       default=None)
        lag = (self.source.lag(self._offsets)
               if hasattr(self.source, "lag") else 0)
        xla_stats.note_stream_gauges(
            watermark_delay_ms=(max_seen - wm
                                if wm is not None and max_seen is not None
                                else 0),
            window_state_bytes=self._state.state_bytes(),
            source_lag_records=lag)
        self._epoch += 1
        return final

    # -- the query loop --------------------------------------------------
    def run(self, max_epochs: Optional[int] = None) -> Dict[str, Any]:
        """Drive epochs to end-of-stream (bounded sources) or
        ``max_epochs``; returns a summary dict.  Retryable failures
        (injected chaos, fetch failures) recover from the last committed
        checkpoint; cancellation/deadline propagates after teardown."""
        from blaze_tpu.serving.context import is_cancellation

        interval_s = config.STREAM_EPOCH_INTERVAL_MS.get() / 1e3
        max_recoveries = max(0, config.STREAM_MAX_RECOVERIES.get())
        recoveries = 0
        try:
            while max_epochs is None or self.epochs_committed < max_epochs:
                t0 = time.monotonic()
                try:
                    if self._run_epoch():
                        break
                except _RETRYABLE as exc:
                    recoveries += 1
                    if recoveries > max_recoveries:
                        # recovery budget exhausted: this failure is
                        # fatal to the stream — dump the black box
                        from blaze_tpu.bridge import context as bctx
                        bctx.record_fatal(
                            getattr(self._ctx, "query_id", None)
                            or f"stream-{id(self):x}",
                            f"stream recovery exhausted after "
                            f"{recoveries - 1} recoveries: {exc}",
                            "stream-recovery-exhausted")
                        raise
                    self._recover()
                    continue
                except Exception as exc:
                    if is_cancellation(exc):
                        raise
                    raise
                if interval_s > 0:
                    left = interval_s - (time.monotonic() - t0)
                    if left > 0:
                        if self._ctx is not None:
                            if self._ctx.wait_cancelled(left):
                                self._ctx.check()
                        else:
                            time.sleep(left)
        finally:
            self.close()
        return self.summary()

    def summary(self) -> Dict[str, Any]:
        return {"epochs": self.epochs_committed,
                "rows_emitted": self.rows_emitted,
                "records_consumed": self.records_consumed,
                "recoveries": len(self.recovery_walls_ns),
                "late_side_rows": len(self.late_side),
                "watermark": self._tracker.watermark(),
                "sink_dir": self.sink.dir,
                "checkpoint_dir": self._ckpt.dir}

    def close(self) -> None:
        self._state.close()
        self._sched.cleanup()


def streaming_service_executor(build):
    """Adapter for ``QueryService(executor=...)``: run a streaming query
    under the serving layer's admission, deadline and cancellation.
    ``build(plan, ctx) -> StreamExecutor`` constructs the stream bound
    to the admitted QueryContext; the executor drains it and returns
    the summary as the query result."""

    def _executor(plan, ctx, handle=None):
        stream = build(plan, ctx)
        return stream.run()

    return _executor
