"""Exactly-once streaming sink: per-epoch parquet files behind a
first-wins commit.

The write path mirrors `_run_producer_rss` (plan/stages.py): every
epoch execution — including a replay after recovery — writes its rows
under a FRESH attempt name, and only the attempt referenced by the
epoch's committed checkpoint manifest is promoted to the final
``epoch-NNNNNN.parquet`` name.  A losing attempt (replay of an epoch
whose manifest already exists) is discarded, so downstream readers of
the sink directory see each epoch's output exactly once no matter how
many times the epoch ran.

Promote is idempotent: recovery re-promotes the manifest's attempt if
the process died between commit and rename (the attempt file is the
durable copy until the final name exists).
"""

from __future__ import annotations

import itertools
import os
from typing import List, Optional

import pyarrow as pa
import pyarrow.parquet as pq

from blaze_tpu.ops.sink import write_parquet_atomic
from blaze_tpu.streaming.checkpoint import fsync_dir

_FINAL = "epoch-{epoch:06d}.parquet"


class ExactlyOnceParquetSink:

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(self.dir, exist_ok=True)
        self._attempt_ids = itertools.count()

    def _final_path(self, epoch: int) -> str:
        return os.path.join(self.dir, _FINAL.format(epoch=epoch))

    # -- the two-phase protocol -----------------------------------------
    def write_attempt(self, epoch: int, table: pa.Table) -> str:
        """Phase 1: land this execution's rows under an attempt name
        (crash-safe, never visible to readers).  Returns the path the
        checkpoint manifest must reference."""
        attempt = os.path.join(
            self.dir,
            f"epoch-{epoch:06d}.a{next(self._attempt_ids)}.parquet")
        write_parquet_atomic(table, attempt)
        return attempt

    def promote(self, epoch: int, attempt_path: str) -> bool:
        """Phase 2 (after the manifest committed): publish the winning
        attempt under the final name.  Idempotent — recovery calls this
        again if the process died mid-promote.  Returns True when this
        call published the file."""
        final = self._final_path(epoch)
        if os.path.exists(final):
            self.discard(attempt_path)
            return False
        os.replace(attempt_path, final)
        fsync_dir(self.dir)  # the rename must survive power loss too
        return True

    def discard(self, attempt_path: str) -> None:
        """Drop a losing attempt (its epoch was already committed by an
        earlier execution)."""
        try:
            os.unlink(attempt_path)
        except OSError:
            pass

    def repair(self, epoch: int, attempt_path: Optional[str]) -> None:
        """Recovery: the manifest for `epoch` is committed; make sure
        its sink file is published (promote the referenced attempt if
        the final name is still missing)."""
        if attempt_path and os.path.exists(attempt_path):
            self.promote(epoch, attempt_path)

    # -- readers ---------------------------------------------------------
    def committed_epochs(self) -> List[int]:
        out = []
        for name in os.listdir(self.dir):
            if (name.startswith("epoch-") and name.endswith(".parquet")
                    and ".a" not in name):
                out.append(int(name[len("epoch-"):-len(".parquet")]))
        return sorted(out)

    def committed_table(self) -> pa.Table:
        """All committed epoch outputs, concatenated in epoch order (the
        stream's total sink output — what the bench compares against an
        offline batch run).  Raises only when NO epoch has committed;
        committed-but-all-empty epochs (a query whose windows produced
        no output) yield an empty table with the sink schema."""
        epochs = self.committed_epochs()
        if not epochs:
            raise FileNotFoundError(f"no committed epochs in {self.dir}")
        tables = [pq.read_table(self._final_path(e)) for e in epochs]
        non_empty = [t for t in tables if t.num_rows]
        if non_empty:
            return pa.concat_tables(non_empty)
        return tables[0]  # legitimately empty stream output
