"""Checkpoint manifests for the streaming runtime.

One JSON manifest per committed epoch (``ckpt-NNNNNN.json``): the
per-partition source offsets the NEXT epoch reads from, the watermark
clock, the windowed-agg accumulator snapshot, and the sink attempt the
epoch produced.  Commit is FIRST-WINS and atomic — the manifest is
written to a temp name and ``os.link``ed into place, so a replayed
epoch racing its own earlier attempt can never publish twice (the same
contract `_run_producer_rss` in plan/stages.py gives shuffle map
attempts).  Recovery = read the highest committed manifest and restore
everything from it; an uncommitted epoch left no manifest, so its
records replay from the previous offsets.

Fault site ``checkpoint-commit`` fires BEFORE the link, modeling a
crash between the sink attempt and the commit — the window where
at-least-once systems double-emit and this design must not.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from blaze_tpu import faults

_PREFIX = "ckpt-"
_SUFFIX = ".json"


def fsync_dir(path: str) -> None:
    """Make a just-linked/renamed directory entry power-loss durable:
    fsync of the FILE orders its data, but the entry itself lives in
    the parent directory's metadata and needs its own fsync.  Best
    effort — some platforms/filesystems refuse directory fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class CheckpointManager:
    """Manifest directory driver (single writer per streaming query;
    crash-vs-replay races are resolved by the first-wins link)."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(self.dir, exist_ok=True)

    def _path(self, epoch: int) -> str:
        return os.path.join(self.dir, f"{_PREFIX}{epoch:06d}{_SUFFIX}")

    def committed(self, epoch: int) -> bool:
        return os.path.exists(self._path(epoch))

    def commit(self, epoch: int, manifest: dict) -> bool:
        """First-wins commit of one epoch's manifest.  Returns True if
        this call published it, False if a manifest for the epoch was
        already committed (replay detected — caller must discard its
        side effects instead of double-applying them)."""
        faults.maybe_fail("checkpoint-commit", epoch=epoch)
        path = self._path(epoch)
        if os.path.exists(path):
            return False
        payload = json.dumps({"epoch": epoch, **manifest},
                             sort_keys=True).encode("utf-8")
        tmp = f"{path}.a{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        try:
            os.link(tmp, path)  # atomic + exclusive: first attempt wins
        except FileExistsError:
            return False
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        fsync_dir(self.dir)  # the manifest's dir entry must survive too
        from blaze_tpu.bridge import xla_stats
        xla_stats.note_stream_checkpoint(len(payload))
        return True

    def load(self, epoch: int) -> dict:
        with open(self._path(epoch), "rb") as f:
            return json.loads(f.read().decode("utf-8"))

    def epochs(self) -> List[int]:
        out = []
        for name in os.listdir(self.dir):
            if name.startswith(_PREFIX) and name.endswith(_SUFFIX):
                try:
                    out.append(int(name[len(_PREFIX):-len(_SUFFIX)]))
                except ValueError:
                    pass
        return sorted(out)

    def latest(self) -> Optional[Tuple[int, dict]]:
        """Highest committed epoch and its manifest (the recovery
        point), or None before the first commit."""
        epochs = self.epochs()
        if not epochs:
            return None
        e = epochs[-1]
        return e, self.load(e)

    @staticmethod
    def offsets_from(manifest: dict) -> Dict[int, int]:
        return {int(p): int(o)
                for p, o in (manifest.get("offsets") or {}).items()}
