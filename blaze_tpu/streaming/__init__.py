"""Streaming runtime: continuous micro-batch execution with
checkpointed state, event-time watermarks and exactly-once sinks
(docs/streaming.md; ref auron-flink-extension/)."""

from blaze_tpu.streaming.checkpoint import CheckpointManager
from blaze_tpu.streaming.executor import (MemoryStreamSource,
                                          StreamExecutor,
                                          StreamWindowConfig,
                                          streaming_service_executor)
from blaze_tpu.streaming.sink import ExactlyOnceParquetSink

__all__ = ["CheckpointManager", "ExactlyOnceParquetSink",
           "MemoryStreamSource", "StreamExecutor", "StreamWindowConfig",
           "streaming_service_executor"]
