"""Local shuffle exchange: stage boundary without a cluster.

The reference relies on Spark's BlockManager for transport; in spark-local
mode the full native write/read path is still exercised through real files
(SURVEY.md §4 'multi-node without a cluster').  LocalShuffleExchange is that
analog: map partitions write .data/.index via ShuffleWriterExec, reduce
partitions read their file segments via IpcReaderExec — same files, same
frames, same index contract as the distributed deployment.
"""

from __future__ import annotations

import os
import tempfile
import uuid
from typing import List, Optional

import numpy as np

from blaze_tpu.bridge.context import TaskContext, task_scope
from blaze_tpu.bridge.resource import put_resource, remove_resource
from blaze_tpu.faults import FetchFailedError
from blaze_tpu.ops.base import ExecutionPlan
from blaze_tpu.schema import Schema
from blaze_tpu.shuffle.partitioning import Partitioning
from blaze_tpu.shuffle.reader import FileSegmentBlock, IpcReaderExec
from blaze_tpu.shuffle.writer import ShuffleWriterExec


def read_index_file(path: str, expected_partitions: Optional[int] = None,
                    data_file: Optional[str] = None) -> List[int]:
    """Cumulative offsets (ref AuronShuffleWriterBase.scala:68-78).

    A shuffle index is the map task's MapStatus: if it is truncated or
    inconsistent, every slice computed from it is garbage.  Validate the
    shape up front — length a multiple of 8, `expected_partitions`+1
    entries when the reducer count is known, monotone offsets starting
    at 0, last offset within the `.data` file — and raise a clear
    FetchFailedError (callers attach the producer's stage/map identity)
    instead of silently slicing garbage."""

    def bad(why: str) -> FetchFailedError:
        from blaze_tpu.bridge import xla_stats
        xla_stats.note_fetch_failure()
        return FetchFailedError(reason=f"bad shuffle index {path}: {why}")

    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        raise bad(str(e)) from e
    if len(data) == 0 or len(data) % 8:
        raise bad(f"{len(data)} bytes is not a whole number of "
                  f"int64 offsets")
    offsets = np.frombuffer(data, dtype="<i8")
    if expected_partitions is not None \
            and len(offsets) != expected_partitions + 1:
        raise bad(f"{len(offsets)} offsets, want "
                  f"{expected_partitions + 1} for {expected_partitions} "
                  f"reduce partitions (truncated index?)")
    if offsets[0] != 0:
        raise bad(f"first offset {offsets[0]} != 0")
    if len(offsets) > 1 and bool(np.any(np.diff(offsets) < 0)):
        raise bad("offsets are not monotone non-decreasing")
    if data_file is not None:
        try:
            size = os.path.getsize(data_file)
        except OSError as e:
            raise bad(f"data file missing: {e}") from e
        if int(offsets[-1]) > size:
            raise bad(f"last offset {int(offsets[-1])} exceeds data "
                      f"file size {size}")
    return offsets.tolist()


class LocalShuffleExchange(ExecutionPlan):
    """Materializing exchange: runs all map tasks on first reduce pull."""

    def __init__(self, child: ExecutionPlan, partitioning: Partitioning,
                 work_dir: Optional[str] = None, stage_id: int = 0):
        super().__init__([child])
        self.partitioning = partitioning
        self.stage_id = stage_id
        self._dir = work_dir or tempfile.mkdtemp(prefix="blaze-exchange-")
        self._shuffle_id = uuid.uuid4().hex[:12]
        self._materialized = False
        self._map_outputs: List[tuple] = []  # (data_file, offsets)
        self.reader = IpcReaderExec(
            f"shuffle://{self._shuffle_id}", child.schema,
            partitioning.num_partitions)
        self.reader._children = []  # standalone reader node

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    @property
    def num_partitions(self) -> int:
        return self.partitioning.num_partitions

    def _materialize(self) -> None:
        if self._materialized:
            return
        child = self.children[0]
        for map_id in range(child.num_partitions):
            data = os.path.join(self._dir,
                                f"shuffle-{self._shuffle_id}-{map_id}.data")
            index = data.replace(".data", ".index")
            writer = ShuffleWriterExec(child, self.partitioning, data, index)
            writer.metrics = self.metrics  # surface write metrics here
            with task_scope(TaskContext(stage_id=self.stage_id,
                                        partition_id=map_id,
                                        num_partitions=child.num_partitions)):
                list(writer.execute(map_id))
            self._map_outputs.append((data, read_index_file(
                index,
                expected_partitions=self.partitioning.num_partitions,
                data_file=data)))

        def blocks_for(reduce_id: int):
            for map_id, (data, offsets) in enumerate(self._map_outputs):
                length = offsets[reduce_id + 1] - offsets[reduce_id]
                if length:
                    yield FileSegmentBlock(data, offsets[reduce_id], length,
                                           stage_id=self.stage_id,
                                           map_id=map_id)
        put_resource(f"shuffle://{self._shuffle_id}", blocks_for)
        self._materialized = True

    def execute(self, partition: int):
        self._materialize()
        return self.reader.execute(partition)

    def cleanup(self) -> None:
        remove_resource(f"shuffle://{self._shuffle_id}")
        for data, _ in self._map_outputs:
            for p in (data, data.replace(".data", ".index")):
                try:
                    os.unlink(p)
                except OSError:
                    pass
        self._map_outputs = []
        self._materialized = False
