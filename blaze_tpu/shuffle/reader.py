"""Shuffle read + collect operators.

Parity: ipc_reader_exec.rs:47 (pulls BlockObjects registered by the engine's
reader in the resource map — file segments / byte buffers / channels,
:277-359), ipc_writer_exec.rs (collect-to-driver IPC stream), and
ffi_reader_exec.rs (row-to-columnar input imported over Arrow FFI; here the
in-process analog imports an iterator of Arrow batches).
"""

from __future__ import annotations

import io
from dataclasses import dataclass
from typing import BinaryIO, Callable, Iterator, List, Optional, Union

import pyarrow as pa

from blaze_tpu import faults
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.bridge.resource import get_resource
from blaze_tpu.faults import (FetchFailedError, InjectedFault,
                              ShuffleChecksumError)
from blaze_tpu.ops.base import BatchIterator, CoalesceStream, ExecutionPlan
from blaze_tpu.schema import Schema
from blaze_tpu.shuffle.ipc import IpcCompressionReader, IpcCompressionWriter


@dataclass
class FileSegmentBlock:
    """(path, offset, length) — the FileSegment fast path
    (ref ipc_reader_exec.rs:277).  stage_id/map_id carry the writing
    map task's lineage so a corrupted/truncated segment can be traced
    back to — and re-produced by — exactly that task."""

    path: str
    offset: int
    length: int
    stage_id: int = -1
    map_id: int = -1


Block = Union[FileSegmentBlock, bytes, BinaryIO]


def read_block(block: Block) -> Iterator[pa.RecordBatch]:
    if isinstance(block, FileSegmentBlock):
        if block.length == 0:
            return
        try:
            faults.maybe_fail("shuffle-read", path=block.path)
            yield from _read_segment(block)
        except (ShuffleChecksumError, EOFError, OSError,
                InjectedFault) as e:
            # the Spark FetchFailed contract: a block that cannot be
            # read back intact (bit rot, truncation, lost file, injected
            # fetch failure) names its producer so the DAG scheduler can
            # re-run just that map task instead of failing the query
            from blaze_tpu.bridge import xla_stats
            xla_stats.note_fetch_failure()
            raise FetchFailedError(
                block.stage_id, block.map_id,
                f"{block.path}@{block.offset}+{block.length}: {e}") from e
    elif isinstance(block, (bytes, bytearray, memoryview)):
        yield from IpcCompressionReader(io.BytesIO(block)).read_batches()
    else:  # file-like channel
        yield from IpcCompressionReader(block).read_batches()


def _read_segment(block: FileSegmentBlock) -> Iterator[pa.RecordBatch]:
    # mmap fast path: raw frames decode zero-copy against the page
    # cache (the FileSegment mmap read of ipc_reader_exec.rs:277);
    # the pa.py_buffer keeps the mapping alive as long as any batch
    # references it
    buf = None
    try:
        import mmap
        with open(block.path, "rb") as f:
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        buf = pa.py_buffer(mm).slice(block.offset, block.length)
    except (OSError, ValueError):
        buf = None  # exotic FS / zero-length mapping: buffered path
    if buf is not None:
        # decode OUTSIDE the fallback guard: a mid-stream decode
        # error must propagate, not restart the block and hand
        # duplicate batches downstream
        from blaze_tpu.shuffle.ipc import read_frames_from_buffer
        yield from read_frames_from_buffer(buf)
        return
    with open(block.path, "rb") as f:
        f.seek(block.offset)
        yield from IpcCompressionReader(f, limit=block.length).read_batches()


class IpcReaderExec(ExecutionPlan):
    """Reads shuffle blocks for this partition from the resource map.

    The resource value is either an iterator/list of Blocks, or a callable
    `partition -> iterable of Blocks` (the per-reduce-task registration
    pattern of AuronBlockStoreShuffleReaderBase.scala:29-66).
    """

    def __init__(self, resource_id: str, schema: Schema,
                 num_partitions: int = 1):
        super().__init__()
        self.resource_id = resource_id
        self._schema = schema
        self._num_partitions = num_partitions

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def num_partitions(self) -> int:
        return self._num_partitions

    def execute(self, partition: int) -> BatchIterator:
        def gen():
            for rb in self.arrow_batches(partition):
                yield ColumnBatch.from_arrow(rb)
        return iter(CoalesceStream(gen(), metrics=self.metrics))

    def arrow_batches(self, partition: int):
        """Arrow-resident read: decoded IPC frames go straight to
        Arrow-resident consumers (the reduce-side host agg) without a
        ColumnBatch round trip.  Segment reads + IPC decode run on the
        prefetch worker so reduce-side compute overlaps them
        (kill-switch auron.tpu.io.prefetch)."""
        from blaze_tpu.ops.base import prefetch
        return prefetch(self._read_blocks(partition), name="ipc_reader")

    def _read_blocks(self, partition: int):
        from blaze_tpu.bridge.context import current_task
        source = get_resource(self.resource_id)
        if source is None:
            raise KeyError(f"shuffle resource {self.resource_id!r} not found")
        blocks = source(partition) if callable(source) else source
        ctx = current_task()
        for block in blocks:
            # per-block cancellation point: a cancelled query stops
            # fetching mid-shuffle instead of draining every segment
            ctx.check_running()
            for rb in read_block(block):
                self.metrics.add("io_bytes", rb.nbytes)
                yield rb


class IpcWriterExec(ExecutionPlan):
    """Writes the child stream as framed IPC into a host sink — the
    collect()-to-driver path (ref ipc_writer_exec.rs)."""

    def __init__(self, child: ExecutionPlan,
                 sink_factory: Callable[[int], BinaryIO]):
        super().__init__([child])
        self._sink_factory = sink_factory

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self, partition: int) -> BatchIterator:
        sink = self._sink_factory(partition)
        w = IpcCompressionWriter(sink)
        for batch in self.children[0].execute(partition):
            rb = batch.compact().to_arrow()
            if rb.num_rows:
                w.write_batch(rb)
                self.metrics.add("output_rows", rb.num_rows)
                self.metrics.add("io_bytes", rb.nbytes)
        w.finish()
        return iter(())


class FFIReaderExec(ExecutionPlan):
    """Imports host-exported Arrow batches (the ConvertToNative path,
    ref ffi_reader_exec.rs; in-process, 'FFI' is a zero-copy handoff of
    pyarrow batches through the resource map)."""

    def __init__(self, resource_id: str, schema: Schema,
                 num_partitions: int = 1):
        super().__init__()
        self.resource_id = resource_id
        self._schema = schema
        self._num_partitions = num_partitions

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def num_partitions(self) -> int:
        return self._num_partitions

    def execute(self, partition: int) -> BatchIterator:
        source = get_resource(self.resource_id)
        if source is None:
            raise KeyError(f"ffi resource {self.resource_id!r} not found")
        batches = source(partition) if callable(source) else source
        for rb in batches:
            yield ColumnBatch.from_arrow(rb)
