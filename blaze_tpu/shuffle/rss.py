"""Celeborn-shaped remote-shuffle-service backend.

Parity: the reference ships concrete RSS integrations (Celeborn 0.5/0.6,
Uniffle — /root/reference/thirdparty/auron-celeborn-0.5/, writers over
`AuronRssPartitionWriterBase.write(partition, bytes)` pushed from
native RssWriter, shuffle/rss.rs:21-45).  This module is the analogous
concrete backend for this engine: a push-based shuffle client whose
storage is any shared directory (NFS / FUSE / object-store mount),
speaking the Celeborn protocol shape —

  * map tasks PUSH partition-addressed byte frames as they are produced
    (not a terminal .data file): `push(partition, payload)`;
  * a push is ATOMIC and IDEMPOTENT (tmp-file + rename, addressed by
    `(map, attempt, seq)`), so a task retry after a mid-push failure
    re-sends frames without corrupting or duplicating data;
  * `mapper_end` commits one attempt's manifest (per-partition frame
    counts — Celeborn's MapperEnd/CommitFiles handshake).  Reducers
    accept exactly ONE committed attempt per map (the FIRST to commit,
    Celeborn's attempt-dedup) and read its frames in seq order;
  * reducers block on the all-maps-committed barrier (MapStatus analog)
    with a timeout, then stream each frame as an ipc_reader block.

Wire integration: `client.partition_writer(map_id, attempt)` returns the
`(partition, bytes) -> None` callable the planner's `rss_shuffle_writer`
hook consumes (plan/planner.py `rss_resource_id`), and
`client.reader_blocks(partition)` feeds `ipc_reader` resources — both
ends ride the existing framed-IPC batch format unchanged.
"""

from __future__ import annotations

import json
import os
import re
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

_FRAME = re.compile(r"^m(\d+)-a(\d+)-s(\d+)\.push(z?)$")

#: scheme prefix selecting the socket backend in auron.tpu.shuffle.service
SOCKET_SCHEME = "socket://"


def _pack_put(payload: bytes) -> Tuple[bytes, str]:
    """Wire form of one partition put.  With io.compression.workerFrames
    on, the put is wrapped in ONE compressed control frame (same
    self-describing [codec|FLAG_CRC][len][crc] layout as the shuffle
    block frames) and lands as `.pushz`; the suffix keys the read-side
    unwrap, so mixed-codec pushes from differently-configured writers
    coexist in one shuffle.  Compression that would grow the put (the
    inner IPC frames are often already codec-compressed) falls back to
    the raw `.push` form — accounting only counts real savings."""
    from blaze_tpu import config
    if config.IO_COMPRESSION_WORKER_FRAMES.get():
        from blaze_tpu.shuffle.ipc import (
            CODEC_RAW, _CRC, _get_codec, _HEADER, pack_control_frame)
        codec = _get_codec()
        if codec != CODEC_RAW:
            frame = pack_control_frame(payload, codec)
            saved = (_HEADER.size + _CRC.size + len(payload)) - len(frame)
            if saved > 0:
                from blaze_tpu.bridge import xla_stats
                xla_stats.note_frame_compression("rss", saved)
                return frame, "pushz"
    return payload, "push"


def _unpack_put(data: bytes) -> bytes:
    """Invert `_pack_put`'s compressed form: CRC-verify the wire bytes,
    then decode by the frame's own codec byte."""
    from blaze_tpu.shuffle.ipc import (
        _check_frame_byte, _CRC, _decompress, FLAG_CRC, _HEADER,
        _verify_crc)
    raw_codec, length = _HEADER.unpack_from(data)
    codec = _check_frame_byte(raw_codec)
    pos = _HEADER.size
    if raw_codec & FLAG_CRC:
        (crc,) = _CRC.unpack_from(data, pos)
        pos += _CRC.size
        _verify_crc(crc, data[pos:pos + length])
    return _decompress(codec, data[pos:pos + length])


class RssPushClient:
    """One shuffle's client handle (map or reduce side)."""

    def __init__(self, root: str, shuffle_id: str, num_maps: int,
                 num_reduces: int, use_hardlinks: bool = True):
        self.root = os.path.join(root, f"rss-{shuffle_id}")
        self.shuffle_id = shuffle_id
        self.num_maps = num_maps
        self.num_reduces = num_reduces
        # False forces the no-hardlink commit arbitration (claim file)
        # even where os.link works — tests and the speculation soak
        # exercise the FUSE/object-store code path deterministically
        self.use_hardlinks = use_hardlinks
        for p in range(num_reduces):
            os.makedirs(os.path.join(self.root, f"part-{p}"),
                        exist_ok=True)

    # -- map side ----------------------------------------------------------

    def partition_writer(self, map_id: int, attempt: int = 0
                         ) -> "RssPartitionWriter":
        return RssPartitionWriter(self, map_id, attempt)

    def _push(self, map_id: int, attempt: int, partition: int,
              seq: int, payload: bytes) -> None:
        d = os.path.join(self.root, f"part-{partition}")
        wire, suffix = _pack_put(payload)
        final = os.path.join(d, f"m{map_id}-a{attempt}-s{seq}.{suffix}")
        if os.path.exists(final):
            return  # idempotent retry of an already-landed frame
        tmp = final + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(wire)
        os.replace(tmp, final)  # atomic publish

    def _committed_attempt(self, map_id: int):
        """Attempt id of the committed manifest for `map_id`, or None."""
        try:
            with open(os.path.join(self.root, f"commit-m{map_id}")) as f:
                return int(json.load(f)["attempt"])
        except (OSError, ValueError, KeyError):
            return None

    def _commit(self, map_id: int, attempt: int,
                counts: Dict[int, int]) -> bool:
        """MapperEnd: publish the attempt manifest.  First committed
        attempt per map wins; later attempts are REJECTED (Celeborn's
        server-arbitrated attempt dedup) on every storage flavor.

        Returns True when this attempt is the committed one (including
        an idempotent re-commit of the same attempt after a lost result
        frame), False when a different attempt won — the caller's output
        is dead and readers will never see it."""
        final = os.path.join(self.root, f"commit-m{map_id}")
        committed = self._committed_attempt(map_id)
        if committed is not None:
            return committed == attempt
        tmp = final + f".tmp.a{attempt}.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"attempt": attempt,
                       "counts": {str(k): v for k, v in counts.items()}},
                      f)
        if self.use_hardlinks:
            try:
                os.link(tmp, final)  # atomic first-wins where supported
                os.unlink(tmp)
                return True
            except FileExistsError:
                os.unlink(tmp)
                return self._committed_attempt(map_id) == attempt
            except OSError:
                pass  # mount lacks hard links: claim-file arbitration
        # FUSE / object-store mounts without hard links: an O_EXCL
        # claim file names the winning attempt BEFORE the manifest
        # rename, so a late attempt is rejected instead of the old
        # last-wins os.replace overwriting the winner
        claim = final + ".owner"
        try:
            fd = os.open(claim, os.O_WRONLY | os.O_CREAT | os.O_EXCL,
                         0o644)
            try:
                os.write(fd, str(attempt).encode())
            finally:
                os.close(fd)
        except FileExistsError:
            os.unlink(tmp)
            try:
                with open(claim) as f:
                    return int(f.read().strip() or "-1") == attempt
            except (OSError, ValueError):
                return False
        os.replace(tmp, final)
        return True

    # -- reduce side -------------------------------------------------------

    def wait_for_maps(self, timeout_s: float = 60.0,
                      poll_s: float = 0.02) -> List[dict]:
        """All-maps-committed barrier; returns each map's winning
        manifest.  Raises TimeoutError naming the stragglers."""
        deadline = time.monotonic() + timeout_s
        manifests: List[dict] = [None] * self.num_maps  # type: ignore
        while True:
            missing = []
            for m in range(self.num_maps):
                if manifests[m] is not None:
                    continue
                path = os.path.join(self.root, f"commit-m{m}")
                if os.path.exists(path):
                    with open(path) as f:
                        manifests[m] = json.load(f)
                else:
                    missing.append(m)
            if not missing:
                return manifests  # type: ignore[return-value]
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"rss shuffle {self.shuffle_id}: maps {missing} "
                    f"never committed within {timeout_s:g}s")
            time.sleep(poll_s)

    def reader_blocks(self, partition: int,
                      timeout_s: float = 60.0) -> List[bytes]:
        """One reduce partition's frames: only the committed attempt of
        each map contributes, frames in push order, duplicates (from
        re-pushed idempotent frames) collapse by seq."""
        manifests = self.wait_for_maps(timeout_s)
        d = os.path.join(self.root, f"part-{partition}")
        by_map: Dict[int, Dict[int, str]] = {}
        for name in os.listdir(d):
            m = _FRAME.match(name)
            if not m:
                continue
            map_id, attempt, seq = (int(m.group(1)), int(m.group(2)),
                                    int(m.group(3)))
            if attempt != manifests[map_id]["attempt"]:
                continue  # uncommitted attempt's leftovers
            by_map.setdefault(map_id, {})[seq] = os.path.join(d, name)
        blocks: List[bytes] = []
        for map_id in range(self.num_maps):
            want = int(manifests[map_id]["counts"].get(str(partition), 0))
            frames = by_map.get(map_id, {})
            # only seqs below the committed count matter: a crashed run of
            # the SAME attempt may have left higher-seq frames behind that
            # the committed retry never re-pushed — those are garbage, not
            # lost pushes
            committed = {s: p for s, p in frames.items() if s < want}
            if len(committed) != want:
                raise IOError(
                    f"rss shuffle {self.shuffle_id} part {partition}: "
                    f"map {map_id} committed {want} frames, found "
                    f"{sorted(committed)} (lost pushes)")
            for seq in sorted(committed):
                with open(committed[seq], "rb") as f:
                    data = f.read()
                if committed[seq].endswith("z"):
                    data = _unpack_put(data)
                blocks.append(data)
        return blocks

    def cleanup(self) -> None:
        import shutil
        shutil.rmtree(self.root, ignore_errors=True)


# -- socket backend ---------------------------------------------------------
#
# The directory backend above needs a shared mount; the socket backend
# needs only a reachable address — map outputs live with the RSS server
# process, not with the replica that produced them, so a replica dying
# mid-query loses NOTHING already pushed (VERDICT item 7, the
# Celeborn-server deployment shape).  Same manifest protocol, same
# first-wins attempt arbitration (the server arbitrates with the
# directory backend's own commit path), carried over the length-prefixed
# CRC32C control frames from shuffle/ipc.py.


def _send_msg(sock, obj) -> None:
    import pickle
    from blaze_tpu.shuffle.ipc import sock_send_frame
    sock_send_frame(sock, pickle.dumps(obj, protocol=4))


def _recv_msg(sock):
    import pickle
    from blaze_tpu.shuffle.ipc import sock_recv_frame
    payload = sock_recv_frame(sock)
    return None if payload is None else pickle.loads(payload)


class RssSocketServer:
    """One RSS endpoint: accepts framed manifest-protocol requests and
    serves them against a private storage directory via the directory
    backend (so both backends share one commit-arbitration code path —
    a race the directory tier rejects is rejected here too)."""

    def __init__(self, root: str, host: str = "127.0.0.1",
                 port: int = 0):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self.host, self.port = self._listener.getsockname()[:2]
        self._clients: Dict[str, RssPushClient] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        """The `auron.tpu.shuffle.service` value selecting this server."""
        return f"{SOCKET_SCHEME}{self.host}:{self.port}"

    def start(self) -> "RssSocketServer":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="blaze-rss-accept",
            daemon=True)
        self._accept_thread.start()
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _peer = self._listener.accept()
            except OSError:
                return  # listener closed
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="blaze-rss-conn", daemon=True).start()

    def _dir_client(self, msg) -> RssPushClient:
        sid = str(msg["shuffle_id"])
        with self._lock:
            client = self._clients.get(sid)
            if client is None:
                client = RssPushClient(
                    self.root, sid, int(msg["num_maps"]),
                    int(msg["num_reduces"]),
                    use_hardlinks=bool(msg.get("use_hardlinks", True)))
                self._clients[sid] = client
        return client

    def _serve_conn(self, conn) -> None:
        from blaze_tpu.shuffle.ipc import FrameTransportClosed
        try:
            while True:
                try:
                    msg = _recv_msg(conn)
                except (FrameTransportClosed, ConnectionError, OSError):
                    return  # peer died mid-frame: nothing to answer
                if msg is None:
                    return  # clean close between frames
                try:
                    reply = self._handle(msg)
                except TimeoutError as e:
                    reply = {"ok": False, "kind": "timeout",
                             "error": str(e)}
                except (IOError, OSError) as e:
                    reply = {"ok": False, "kind": "io", "error": str(e)}
                except Exception as e:  # protocol-level failure
                    reply = {"ok": False, "kind": "error",
                             "error": f"{type(e).__name__}: {e}"}
                try:
                    _send_msg(conn, reply)
                except (FrameTransportClosed, ConnectionError, OSError):
                    return  # reply torn: client re-requests idempotently
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, msg: dict) -> dict:
        kind = msg.get("kind")
        if kind == "hello":
            return {"ok": True, "root": self.root, "pid": os.getpid()}
        client = self._dir_client(msg)
        if kind == "push":
            client._push(int(msg["map"]), int(msg["attempt"]),
                         int(msg["partition"]), int(msg["seq"]),
                         msg["payload"])
            return {"ok": True}
        if kind == "commit":
            won = client._commit(
                int(msg["map"]), int(msg["attempt"]),
                {int(k): int(v) for k, v in msg["counts"].items()})
            return {"ok": True, "won": won}
        if kind == "committed":
            return {"ok": True,
                    "attempt": client._committed_attempt(int(msg["map"]))}
        if kind == "wait":
            return {"ok": True, "manifests": client.wait_for_maps(
                timeout_s=float(msg.get("timeout_s", 60.0)))}
        if kind == "blocks":
            return {"ok": True, "blocks": client.reader_blocks(
                int(msg["partition"]),
                timeout_s=float(msg.get("timeout_s", 60.0)))}
        if kind == "cleanup":
            with self._lock:
                self._clients.pop(str(msg["shuffle_id"]), None)
            client.cleanup()
            return {"ok": True}
        raise ValueError(f"unknown rss request kind {kind!r}")

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass


class RssSocketClient:
    """Drop-in for RssPushClient speaking the manifest protocol over a
    socket.  Every request is idempotent or server-arbitrated
    (push = rename-idempotent, commit = first-wins), so a torn frame or
    dead connection is survived by reconnect + re-send — the retry can
    never corrupt or double-commit.  `self.root` mirrors the server's
    storage path for this shuffle (loopback white-box introspection;
    the wire protocol itself never touches it)."""

    #: reconnect+resend budget per request (each retry is a fresh
    #: connection; beyond this the transport error propagates retryable)
    _MAX_SENDS = 3

    def __init__(self, addr, shuffle_id: str, num_maps: int,
                 num_reduces: int, use_hardlinks: bool = True,
                 timeout_s: float = 30.0):
        if isinstance(addr, str):
            if addr.startswith(SOCKET_SCHEME):
                addr = addr[len(SOCKET_SCHEME):]
            host, _, port = addr.rpartition(":")
            addr = (host or "127.0.0.1", int(port))
        self._addr = (addr[0], int(addr[1]))
        self.shuffle_id = shuffle_id
        self.num_maps = num_maps
        self.num_reduces = num_reduces
        self.use_hardlinks = use_hardlinks
        self._timeout_s = timeout_s
        self._sock = None
        self._lock = threading.RLock()
        hello = self._request({"kind": "hello"})
        self.root = os.path.join(hello["root"], f"rss-{shuffle_id}")

    # -- transport ---------------------------------------------------------

    def _connect(self, timeout_s: float):
        sock = socket.create_connection(self._addr, timeout=timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _request(self, msg: dict, timeout_s: Optional[float] = None
                 ) -> dict:
        from blaze_tpu.shuffle.ipc import FrameTransportClosed
        msg.setdefault("shuffle_id", self.shuffle_id)
        msg.setdefault("num_maps", self.num_maps)
        msg.setdefault("num_reduces", self.num_reduces)
        msg.setdefault("use_hardlinks", self.use_hardlinks)
        budget = (timeout_s or 0.0) + self._timeout_s
        last: Optional[BaseException] = None
        with self._lock:
            for _attempt in range(self._MAX_SENDS):
                try:
                    if self._sock is None:
                        self._sock = self._connect(budget)
                    self._sock.settimeout(budget)
                    _send_msg(self._sock, msg)
                    reply = _recv_msg(self._sock)
                    if reply is None:
                        raise FrameTransportClosed(
                            "rss server closed before replying")
                    break
                except (FrameTransportClosed, ConnectionError,
                        OSError, EOFError) as e:
                    last = e
                    self._drop()
            else:
                raise FrameTransportClosed(
                    f"rss server {self._addr[0]}:{self._addr[1]} "
                    f"unreachable after {self._MAX_SENDS} attempts"
                ) from last
        if reply.get("ok"):
            return reply
        err = reply.get("error", "rss request failed")
        if reply.get("kind") == "timeout":
            raise TimeoutError(err)
        if reply.get("kind") == "io":
            raise IOError(err)
        raise RuntimeError(err)

    # -- the RssPushClient surface ----------------------------------------

    def partition_writer(self, map_id: int, attempt: int = 0
                         ) -> "RssPartitionWriter":
        return RssPartitionWriter(self, map_id, attempt)

    def _push(self, map_id: int, attempt: int, partition: int,
              seq: int, payload: bytes) -> None:
        self._request({"kind": "push", "map": map_id,
                       "attempt": attempt, "partition": partition,
                       "seq": seq, "payload": payload})

    def _commit(self, map_id: int, attempt: int,
                counts: Dict[int, int]) -> bool:
        return bool(self._request(
            {"kind": "commit", "map": map_id, "attempt": attempt,
             "counts": {int(k): int(v) for k, v in counts.items()}}
        )["won"])

    def _committed_attempt(self, map_id: int):
        return self._request({"kind": "committed",
                              "map": map_id})["attempt"]

    def wait_for_maps(self, timeout_s: float = 60.0,
                      poll_s: float = 0.02) -> List[dict]:
        # transport deadline > server-side wait deadline, so the
        # server's TimeoutError reply wins over a raw socket timeout
        return self._request({"kind": "wait", "timeout_s": timeout_s},
                             timeout_s=timeout_s + 10.0)["manifests"]

    def reader_blocks(self, partition: int,
                      timeout_s: float = 60.0) -> List[bytes]:
        return self._request(
            {"kind": "blocks", "partition": partition,
             "timeout_s": timeout_s}, timeout_s=timeout_s + 10.0)["blocks"]

    def cleanup(self) -> None:
        try:
            self._request({"kind": "cleanup"})
        except Exception:
            pass  # cleanup is best-effort on both backends
        with self._lock:
            self._drop()

    def close(self) -> None:
        with self._lock:
            self._drop()


def rss_client_for(root: str, shuffle_id: str, num_maps: int,
                   num_reduces: int, use_hardlinks: bool = True):
    """Backend selection off the `auron.tpu.shuffle.service` value: a
    `socket://host:port` address speaks the socket protocol, anything
    else is a shared-storage directory root.  Both return the same
    client surface, so the scheduler's RSS path is backend-blind."""
    if root.startswith(SOCKET_SCHEME):
        return RssSocketClient(root, shuffle_id, num_maps, num_reduces,
                               use_hardlinks=use_hardlinks)
    return RssPushClient(root, shuffle_id, num_maps, num_reduces,
                         use_hardlinks=use_hardlinks)


class RssPartitionWriter:
    """Per-task push handle: the `AuronRssPartitionWriterBase` analog.
    Callable with `(partition, payload)` so it plugs straight into the
    planner's `rss_shuffle_writer` resource hook."""

    def __init__(self, client: RssPushClient, map_id: int, attempt: int):
        self._client = client
        self.map_id = map_id
        self.attempt = attempt
        self._seq: Dict[int, int] = {}
        self._closed = False

    def __call__(self, partition: int, payload: bytes) -> None:
        self.write(partition, payload)

    def write(self, partition: int, payload: bytes) -> None:
        if self._closed:
            raise RuntimeError("writer already committed")
        if not payload:
            return
        seq = self._seq.get(partition, 0)
        self._client._push(self.map_id, self.attempt, partition, seq,
                           payload)
        self._seq[partition] = seq + 1

    def commit(self) -> bool:
        """MapperEnd: publishes this attempt's manifest.  Returns True
        when this attempt won the first-wins commit race, False when a
        sibling attempt already committed and this output is dead."""
        self._closed = True
        return self._client._commit(self.map_id, self.attempt,
                                    dict(self._seq))
