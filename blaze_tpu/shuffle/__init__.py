"""Shuffle: repartitioners, framed IPC blocks, .data/.index files.

Ref: datafusion-ext-plans/src/shuffle/ + io/ipc_compression.rs.
"""

from blaze_tpu.shuffle.ipc import (IpcCompressionReader, IpcCompressionWriter,
                                   read_batches_from_bytes,
                                   write_batches_to_bytes)

__all__ = ["IpcCompressionReader", "IpcCompressionWriter",
           "read_batches_from_bytes", "write_batches_to_bytes"]
