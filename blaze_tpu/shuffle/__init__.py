"""Shuffle: repartitioners, framed IPC blocks, .data/.index files.

Ref: datafusion-ext-plans/src/shuffle/ + io/ipc_compression.rs.
"""

from blaze_tpu.faults import FetchFailedError, ShuffleChecksumError
from blaze_tpu.shuffle.ipc import (IpcCompressionReader, IpcCompressionWriter,
                                   read_batches_from_bytes,
                                   write_batches_to_bytes)
from blaze_tpu.shuffle.partitioning import (HashPartitioning, Partitioning,
                                            RangePartitioning,
                                            RoundRobinPartitioning,
                                            SinglePartitioning,
                                            sample_range_bounds)
from blaze_tpu.shuffle.reader import (FFIReaderExec, FileSegmentBlock,
                                      IpcReaderExec, IpcWriterExec)
from blaze_tpu.shuffle.writer import (RssShuffleWriterExec,
                                      ShuffleRepartitioner, ShuffleWriterExec)
from blaze_tpu.shuffle.exchange import LocalShuffleExchange, read_index_file

__all__ = ["IpcCompressionReader", "IpcCompressionWriter",
           "read_batches_from_bytes", "write_batches_to_bytes",
           "HashPartitioning", "Partitioning", "RangePartitioning",
           "RoundRobinPartitioning", "SinglePartitioning",
           "sample_range_bounds",
           "FFIReaderExec", "FileSegmentBlock", "IpcReaderExec",
           "IpcWriterExec", "RssShuffleWriterExec", "ShuffleRepartitioner",
           "ShuffleWriterExec", "LocalShuffleExchange", "read_index_file",
           "FetchFailedError", "ShuffleChecksumError"]
