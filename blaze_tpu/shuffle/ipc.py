"""Framed compressed Arrow-IPC block format.

Parity: datafusion-ext-commons/src/io/ipc_compression.rs (`:35`
IpcCompressionWriter, `:135` IpcCompressionReader) — the one wire/disk format
shared by shuffle `.data` files, spill files and broadcast byte arrays.

Frame layout (little-endian):
    [u8  codec]  low 7 bits: 0 = raw, 1 = zstd, 2 = lz4-frame (the
                 reference's default shuffle codec, via Arrow C++; ref
                 SPILL_COMPRESSION_CODEC).  High bit (FLAG_CRC, format
                 v2): a u32 CRC32C of the payload follows the length.
    [u32 length] compressed payload size
    [u32 crc32c] only when FLAG_CRC — checksum of the payload bytes
    [payload]    one Arrow IPC *stream* (schema + N record batches)

Frames are self-describing and concatenable: a reader can start at any frame
boundary, which is what the shuffle `.index` file points at.  Batches are
buffered until the target frame size so small batches amortize compression
(ref auron.shuffle.compression.target.buf.size).

Integrity (format v2, auron.tpu.shuffle.checksum): each frame carries a
CRC32C over its (compressed) payload, verified on every read; a mismatch
raises ShuffleChecksumError, which file-segment readers upgrade to
FetchFailedError so the DAG scheduler can re-run exactly the map task
that wrote the block.  Codec bytes with unknown flag/codec bits are
rejected with a clear error instead of decoding garbage — a reader older
than the frame format fails loudly, never silently.

Stream transports (fleet sockets, RSS socket backend): the same frames
ride TCP via `sock_send_frame` / `recv_control_frame`, which loop on
short recv until the length prefix is satisfied and classify a mid-frame
EOF as FrameTransportClosed — retryable peer loss in the WorkerCrashed /
ConnectionError taxonomy — keeping ShuffleChecksumError reserved for a
COMPLETE frame whose CRC32C genuinely mismatches.
"""

from __future__ import annotations

import io
import struct
from typing import BinaryIO, Iterator, List, Optional

import pyarrow as pa

from blaze_tpu import config, faults
from blaze_tpu.faults import ShuffleChecksumError

class FrameTransportClosed(ConnectionError):
    """A stream transport (TCP socket) ended mid-frame: the peer died or
    the connection was reset between the length prefix and the payload.
    This is LOSS, not corruption — the bytes that did arrive were never
    CRC-mismatched — so it must classify retryable (the WorkerCrashed /
    ConnectionError taxonomy: re-route, re-connect, re-send), never as a
    ShuffleChecksumError that would trigger lineage recovery for a block
    that was simply cut off in flight."""


_HEADER = struct.Struct("<BI")
_CRC = struct.Struct("<I")
CODEC_RAW = 0
CODEC_ZSTD = 1
CODEC_LZ4 = 2
FLAG_CRC = 0x80
_CODEC_MASK = 0x7F
_KNOWN_CODECS = (CODEC_RAW, CODEC_ZSTD, CODEC_LZ4)

try:
    from google_crc32c import value as _crc32c_impl

    def _crc32c(data) -> int:
        if not isinstance(data, bytes):
            data = bytes(data)  # google_crc32c rejects memoryviews
        return _crc32c_impl(data)
except ImportError:  # pragma: no cover - image always ships google_crc32c
    import zlib

    def _crc32c(data) -> int:
        return zlib.crc32(data) & 0xFFFFFFFF


def _check_frame_byte(raw_codec: int) -> int:
    """Validate a frame's codec byte; returns the codec id."""
    codec = raw_codec & _CODEC_MASK
    if codec not in _KNOWN_CODECS or (raw_codec & ~(FLAG_CRC | _CODEC_MASK)):
        raise ShuffleChecksumError(
            f"unknown shuffle frame codec byte 0x{raw_codec:02x}: frame "
            f"written by a newer format than this reader understands")
    return codec


def _verify_crc(expected: int, payload) -> None:
    actual = _crc32c(payload)
    if actual != expected:
        raise ShuffleChecksumError(
            f"shuffle frame CRC32C mismatch: stored 0x{expected:08x}, "
            f"computed 0x{actual:08x} over {len(payload)} bytes "
            f"(corrupted block)")


def pack_control_frame(payload: bytes, codec: int = CODEC_RAW) -> bytes:
    """One CRC32C-protected frame around an opaque payload — the
    worker wire protocol's message framing (parallel/workers.py rides
    these for pickled task/heartbeat/result messages, trace context
    included).  Layout matches the shuffle block frames exactly:
    [codec|FLAG_CRC][u32 len][u32 crc32c][payload], so a torn or
    bit-rotted control frame surfaces as the same EOFError /
    ShuffleChecksumError taxonomy the retry machinery classifies.

    `codec` (io.compression.workerFrames) compresses the payload with
    the shuffle block codec; the frame byte self-describes the choice,
    so a reader built for CODEC_RAW-only peers still interoperates —
    compression is skipped whenever it would grow the frame, keeping
    tiny control messages (heartbeats, acks) raw."""
    if codec != CODEC_RAW:
        body = _compress(codec, payload)
        if len(body) < len(payload):
            return (_HEADER.pack(codec | FLAG_CRC, len(body))
                    + _CRC.pack(_crc32c(body)) + body)
    return (_HEADER.pack(CODEC_RAW | FLAG_CRC, len(payload))
            + _CRC.pack(_crc32c(payload)) + payload)


def recv_exact(read, n: int, *, mid_frame: bool = False):
    """Read exactly `n` bytes from a stream transport, looping on short
    reads (TCP `recv` returns whatever the kernel has buffered, not the
    requested length — the length prefix is only satisfied once the loop
    accumulates it).  Returns None on a clean EOF at a frame boundary
    (`mid_frame=False`, the peer closed between frames); raises
    FrameTransportClosed when the stream ends with a frame partially
    delivered — retryable loss, not a checksum failure."""
    data = read(n)
    if not data:
        if mid_frame:
            raise FrameTransportClosed(
                f"stream closed mid-frame ({n} byte(s) short)")
        return None
    data = bytes(data)
    while len(data) < n:
        more = read(n - len(data))
        if not more:
            raise FrameTransportClosed(
                f"stream closed mid-frame (got {len(data)}/{n} bytes)")
        data += bytes(more)
    return data


def recv_control_frame(read):
    """Read one control frame from a stream transport and return its
    verified, decompressed payload — the socket-side dual of
    `pack_control_frame`.  `read(n)` is any short-read-prone callable
    (socket.recv, file.read).  Returns None on clean EOF before a new
    frame; raises FrameTransportClosed on a torn frame (peer death
    mid-send — retryable) and ShuffleChecksumError only on genuine
    payload corruption (CRC mismatch on a COMPLETE frame)."""
    header = recv_exact(read, _HEADER.size)
    if header is None:
        return None
    raw_codec, length = _HEADER.unpack(header)
    codec = _check_frame_byte(raw_codec)
    crc = None
    if raw_codec & FLAG_CRC:
        (crc,) = _CRC.unpack(recv_exact(read, _CRC.size, mid_frame=True))
    payload = (recv_exact(read, length, mid_frame=True)
               if length else b"")
    if crc is not None:
        _verify_crc(crc, payload)
    return _decompress(codec, payload)


def sock_send_frame(sock, payload: bytes, codec: int = CODEC_RAW) -> None:
    """Send one control frame over a socket.  The `socket-torn-frame`
    fault site models the producing host dying mid-send: the peer
    receives a prefix of the frame and then EOF, which its
    `recv_control_frame` must surface as retryable FrameTransportClosed
    loss — never as corruption, and never as a silent short message."""
    frame = pack_control_frame(payload, codec)
    if faults.fires("socket-torn-frame"):
        try:
            sock.sendall(frame[:max(1, len(frame) // 2)])
        finally:
            sock.close()
        raise FrameTransportClosed("injected torn frame (sender died)")
    sock.sendall(frame)


def sock_recv_frame(sock):
    """Receive one control frame's payload from a socket (None on clean
    EOF); short recvs are looped until the length prefix is satisfied."""
    return recv_control_frame(sock.recv)


def _lz4():
    try:
        return pa.Codec("lz4") if pa.Codec.is_available("lz4") else None
    except Exception:
        return None


def _codec_from_name(name: str) -> int:
    name = name.lower()
    if name == "lz4" and _lz4() is not None:
        return CODEC_LZ4
    return CODEC_ZSTD if name in ("zstd", "zstandard") else CODEC_RAW


def _get_codec() -> int:
    # io.compression.codec governs shuffle frames when explicitly set;
    # otherwise the spill codec key (which governed this framing before
    # the io.* family landed) still applies
    if config.conf.is_set(config.IO_COMPRESSION_CODEC):
        name = config.IO_COMPRESSION_CODEC.get().lower()
    elif config.conf.is_set(config.SPILL_COMPRESSION_CODEC):
        name = config.SPILL_COMPRESSION_CODEC.get().lower()
    else:
        name = config.IO_COMPRESSION_CODEC.get().lower()  # default: lz4
    return _codec_from_name(name)


def _compress(codec: int, payload: bytes) -> bytes:
    if codec == CODEC_LZ4:
        # lz4 payloads lead with the raw size (Arrow's Codec.decompress
        # requires it); the frame codec byte keys the layout
        return (struct.pack("<I", len(payload)) +
                _lz4().compress(payload, asbytes=True))
    if codec == CODEC_ZSTD:
        from blaze_tpu.bridge.native import get_codec
        native = get_codec()
        if native is not None:
            # native frame includes the header; strip it (caller re-adds)
            return native.compress_frame(payload, 1)[_HEADER.size:]
        import zstandard
        return zstandard.ZstdCompressor(level=1).compress(payload)
    return payload


def _decompress(codec: int, payload: bytes) -> bytes:
    if codec == CODEC_LZ4:
        codec_obj = _lz4()
        if codec_obj is None:
            raise RuntimeError(
                "shuffle frame is lz4-compressed but this Arrow build "
                "lacks the lz4 codec; set io.compression.codec=zstd on "
                "the writing side")
        (raw_size,) = struct.unpack_from("<I", payload)
        return codec_obj.decompress(payload[4:], decompressed_size=raw_size,
                                    asbytes=True)
    if codec == CODEC_ZSTD:
        from blaze_tpu.bridge.native import get_codec
        native = get_codec()
        if native is not None:
            try:
                return native.decompress(payload)
            except RuntimeError:
                pass  # streaming-format frame: fall through to python zstd
        import zstandard
        return zstandard.ZstdDecompressor().decompress(payload)
    return payload


class IpcCompressionWriter:
    """Streams record batches into framed compressed IPC blocks."""

    def __init__(self, sink: BinaryIO,
                 target_frame_bytes: Optional[int] = None,
                 codec_name: Optional[str] = None,
                 checksum: Optional[bool] = None):
        self._sink = sink
        self._codec = (_codec_from_name(codec_name) if codec_name
                       else _get_codec())
        self._target = (target_frame_bytes or
                        config.SHUFFLE_COMPRESSION_TARGET_BUF_SIZE.get())
        self._checksum = (config.SHUFFLE_CHECKSUM_ENABLE.get()
                          if checksum is None else checksum)
        self._pending: List[pa.RecordBatch] = []
        self._pending_bytes = 0
        self.raw_bytes_written = 0
        self.frames_written = 0

    def write_batch(self, batch: pa.RecordBatch) -> int:
        """Buffer a batch; flush a frame when the target size is reached.
        Returns the batch's in-memory size (for spill accounting)."""
        nbytes = batch.nbytes
        self._pending.append(batch)
        self._pending_bytes += nbytes
        if self._pending_bytes >= self._target:
            self.flush_frame()
        return nbytes

    def flush_frame(self) -> None:
        if not self._pending:
            return
        buf = io.BytesIO()
        with pa.ipc.new_stream(buf, self._pending[0].schema) as w:
            for b in self._pending:
                w.write_batch(b)
        payload = _compress(self._codec, buf.getvalue())
        if self._checksum:
            # crc first, corruption second: the injected flip models
            # bit-rot AFTER a correct write, which is exactly what the
            # read-side verification must catch
            crc = _crc32c(payload)
            payload = faults.corrupt("shuffle-write", payload)
            self._sink.write(_HEADER.pack(self._codec | FLAG_CRC,
                                          len(payload)))
            self._sink.write(_CRC.pack(crc))
        else:
            payload = faults.corrupt("shuffle-write", payload)
            self._sink.write(_HEADER.pack(self._codec, len(payload)))
        self._sink.write(payload)
        self.raw_bytes_written += self._pending_bytes
        self.frames_written += 1
        self._pending.clear()
        self._pending_bytes = 0

    def finish(self) -> None:
        self.flush_frame()


class IpcCompressionReader:
    """Reads frames until EOF (or a byte limit for file-segment blocks)."""

    def __init__(self, source: BinaryIO, limit: Optional[int] = None):
        self._source = source
        self._remaining = limit

    def _read_exact(self, n: int) -> Optional[bytes]:
        if self._remaining is not None:
            if self._remaining == 0:
                return None
            assert self._remaining >= n, "frame crosses segment boundary"
        data = self._source.read(n)
        if not data:
            return None
        while len(data) < n:
            more = self._source.read(n - len(data))
            if not more:
                raise EOFError("truncated IPC frame")
            data += more
        if self._remaining is not None:
            self._remaining -= n
        return data

    def read_batches(self) -> Iterator[pa.RecordBatch]:
        while True:
            header = self._read_exact(_HEADER.size)
            if header is None:
                return
            raw_codec, length = _HEADER.unpack(header)
            codec = _check_frame_byte(raw_codec)
            crc = None
            if raw_codec & FLAG_CRC:
                crc_bytes = self._read_exact(_CRC.size)
                if crc_bytes is None:
                    raise EOFError("truncated IPC frame checksum")
                (crc,) = _CRC.unpack(crc_bytes)
            payload = self._read_exact(length)
            if payload is None:
                raise EOFError("truncated IPC frame payload")
            faults.maybe_fail("ipc-decode")
            if crc is not None:
                _verify_crc(crc, payload)
            raw = _decompress(codec, payload)
            with pa.ipc.open_stream(io.BytesIO(raw)) as r:
                yield from r


def read_frames_from_buffer(buf: "pa.Buffer") -> Iterator[pa.RecordBatch]:
    """Decode frames straight out of a zero-copy buffer (mmap-backed
    file segment): raw frames hand Arrow IPC a BufferReader over the
    original pages — no payload copy at all; compressed frames fall
    back to a bytes round trip for the decompressor."""
    mv = memoryview(buf)
    pos = 0
    end = len(buf)
    while pos < end:
        raw_codec, length = _HEADER.unpack_from(mv, pos)
        pos += _HEADER.size
        codec = _check_frame_byte(raw_codec)
        faults.maybe_fail("ipc-decode")
        if raw_codec & FLAG_CRC:
            (crc,) = _CRC.unpack_from(mv, pos)
            pos += _CRC.size
            _verify_crc(crc, mv[pos:pos + length])
        if codec == CODEC_RAW:
            payload = buf.slice(pos, length)
            if payload.address % 64:
                # frames sit behind a 5-byte header, so mmap slices are
                # essentially never 64-byte aligned; Acero warns on (and
                # some hardware penalizes) unaligned columnar buffers —
                # one aligned copy is cheaper than per-frame syscall +
                # BytesIO chains and keeps everything downstream safe
                aligned = pa.allocate_buffer(length)
                memoryview(aligned)[:] = memoryview(payload)
                payload = aligned
            with pa.ipc.open_stream(pa.BufferReader(payload)) as r:
                yield from r
        else:
            raw = _decompress(codec, bytes(mv[pos:pos + length]))
            with pa.ipc.open_stream(io.BytesIO(raw)) as r:
                yield from r
        pos += length


def write_batches_to_bytes(batches) -> bytes:
    """One-shot helper (broadcast data, ref NativeBroadcastExchangeBase)."""
    sink = io.BytesIO()
    w = IpcCompressionWriter(sink)
    for b in batches:
        w.write_batch(b)
    w.finish()
    return sink.getvalue()


def read_batches_from_bytes(data: bytes) -> Iterator[pa.RecordBatch]:
    yield from IpcCompressionReader(io.BytesIO(data)).read_batches()
