"""Shuffle write: staged repartitioning + .data/.index files.

Parity: shuffle_writer_exec.rs + shuffle/sort_repartitioner.rs:44
(SortShuffleRepartitioner: BufferedData stages batches, radix-sorts rows by
partition id, writes per-partition framed compressed IPC runs with offsets,
spills under memory pressure and merges spills at shuffle_write;
buffered_data.rs:48) and the file contract consumed by the JVM
(.data + little-endian u64 cumulative-offset .index,
ref AuronShuffleWriterBase.scala:46-85).

TPU-first: partition ids compute ON DEVICE (murmur3+pmod inside the jit'd
stage), then rows group by pid via the same device sort-by-key machinery as
aggregation; the host writes per-partition frames.  Spill files hold the
same per-partition framed layout with an in-memory offset table, so the
final merge is pure sequential IO per partition (no decode).
"""

from __future__ import annotations

import io
import os
import re
import struct
import tempfile
from dataclasses import dataclass, field
from typing import BinaryIO, Callable, Iterator, List, Optional, Sequence, \
    Tuple

import numpy as np
import pyarrow as pa

from blaze_tpu import config
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.bridge.context import current_task
from blaze_tpu.memory import MemConsumer, MemManager
from blaze_tpu.ops.base import BatchIterator, ExecutionPlan
from blaze_tpu.schema import Schema
from blaze_tpu.shuffle.ipc import IpcCompressionWriter
from blaze_tpu.shuffle.partitioning import Partitioning


#: attempt-suffixed index sidecar: `<base>.a<N>.index` — the speculative
#: execution naming scheme (plan/stages.py _map_task_def allocates the
#: attempt ids; un-suffixed paths take the legacy single-attempt commit)
_ATTEMPT_INDEX_RE = re.compile(r"^(?P<base>.+)\.a(?P<attempt>\d+)\.index$")


def promote_attempt_output(data_file: str, index_file: str
                           ) -> Optional[bool]:
    """First-wins commit arbitration for attempt-suffixed shuffle output.

    Every attempt writes its own private `<base>.a<N>.data/.index` pair,
    so concurrent attempts never race on file CONTENT — only on who gets
    to be the committed output.  The arbitration is a claim file created
    with O_EXCL (atomic on POSIX and on the FUSE/object-store mounts
    that lack hard links) recording the winning attempt id, followed by
    ONE os.replace of the winner's index to the canonical `<base>.index`
    path.  A losing attempt deletes its own files, so a cancelled or
    raced loser can never be read.  Readers resolve the winner through
    the claim (resolve_attempt_data) and the single canonical index.

    Returns True when this attempt won, False when a sibling already
    committed (the loser's output is discarded), None when the paths are
    not attempt-suffixed (speculation off: the caller's tmp+os.replace
    discipline already committed atomically and nothing changes)."""
    m = _ATTEMPT_INDEX_RE.match(index_file)
    if m is None:
        return None
    attempt = int(m.group("attempt"))
    final_index = m.group("base") + ".index"
    claim = final_index + ".owner"
    won = False
    try:
        fd = os.open(claim, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o644)
        try:
            os.write(fd, str(attempt).encode())
        finally:
            os.close(fd)
        won = True
    except FileExistsError:
        # a sibling claimed first; an identical-attempt re-commit (task
        # retry after the result frame was lost) is still the winner
        try:
            with open(claim) as f:
                won = int(f.read().strip() or "-1") == attempt
        except (OSError, ValueError):
            won = False
    from blaze_tpu.bridge import xla_stats
    if won:
        if not os.path.exists(index_file):
            # idempotent re-commit after the first promotion already
            # moved this attempt's index to the canonical path (task
            # retry of the winner after a lost result frame)
            return True
        if os.path.exists(final_index):
            # the claim is supposed to make this impossible; count it so
            # the speculation soak's duplicate_output_blocks check sees
            # any double-accept instead of silently overwriting
            xla_stats.note_speculation(duplicate_commits=1)
        os.replace(index_file, final_index)
        return True
    for p in (index_file, data_file):
        try:
            os.unlink(p)
        except OSError:
            pass
    xla_stats.note_speculation(loser_commits_rejected=1)
    return False


def resolve_attempt_data(data_file: str) -> Tuple[str, int]:
    """Map a canonical `<base>.data` path to the committed attempt's
    actual data file.  Returns (path, attempt): the claim file written
    by promote_attempt_output names the winner; without one the legacy
    un-suffixed path is the single attempt (attempt 0)."""
    base = data_file[:-len(".data")]
    claim = base + ".index.owner"
    try:
        with open(claim) as f:
            attempt = int(f.read().strip())
    except (OSError, ValueError):
        return data_file, 0
    return f"{base}.a{attempt}.data", attempt


class _PartitionedSpill:
    """Spill file laid out partition-major with an offset table."""

    def __init__(self):
        fd, self.path = tempfile.mkstemp(prefix="blaze-shuffle-",
                                         suffix=".spill")
        os.close(fd)
        self.offsets: List[int] = []

    def release(self):
        try:
            os.unlink(self.path)
        except OSError:
            pass


class ShuffleRepartitioner(MemConsumer):
    """BufferedData + spill management (ref sort_repartitioner.rs:44)."""

    def __init__(self, partitioning: Partitioning, schema: Schema,
                 metrics=None):
        super().__init__("shuffle")
        self.partitioning = partitioning
        self.schema = schema
        self.metrics = metrics
        self._staged: List[pa.RecordBatch] = []  # with __pid lead column
        self._staged_bytes = 0
        self._spills: List[_PartitionedSpill] = []
        self._metrics = metrics
        self._stream_sink: Optional[BinaryIO] = None
        self._stream_writer: Optional[IpcCompressionWriter] = None
        self._stream_file: Optional[str] = None
        self._stream_tmp: Optional[str] = None

    # -- streaming single-partition mode -----------------------------------
    def open_stream(self, data_file: str) -> bool:
        """Single-reduce-partition local writes stream frames straight
        into the .data file as batches arrive: no staging buffer, no
        end-of-task serialization hump, and upstream compute overlaps
        shuffle IO.  Only valid before the first insert; multi-partition
        layouts still need the staged pid sort."""
        if (self.partitioning.num_partitions != 1 or self._staged
                or self._spills):
            return False
        # write to a task-private temp path, os.replace at finalize: a
        # failed/speculative attempt can never leave a truncated .data
        # at the final path or truncate a sibling attempt's output
        # (AuronShuffleWriterBase's tmp-file + commit discipline)
        self._stream_tmp = (f"{data_file}.inprogress"
                            f".{os.getpid()}.{id(self):x}")
        self._stream_sink = open(self._stream_tmp, "wb")
        self._stream_file = data_file
        return True

    def _stream_write(self, rb) -> None:
        if self._stream_writer is None:
            self._stream_writer = IpcCompressionWriter(
                self._stream_sink,
                codec_name=config.SHUFFLE_FILE_CODEC.get())
        if isinstance(rb, pa.Table):
            for piece in rb.to_batches():
                if piece.num_rows:
                    self._stream_writer.write_batch(piece)
        else:
            self._stream_writer.write_batch(rb)

    def close(self) -> None:
        """Abandon an un-finalized write (task failure/cancel path): the
        stream temp file is removed, the final path never existed, and
        any spill files are released — a query cancelled between spill
        and write() must not leak them."""
        if self._stream_sink is not None:
            try:
                self._stream_sink.close()
            except OSError:
                pass
            try:
                os.unlink(self._stream_tmp)
            except OSError:
                pass
            self._stream_sink = None
            self._stream_writer = None
        if self._spills:
            spills, self._spills = self._spills, []
            for s in spills:
                try:
                    s.release()
                except OSError:
                    pass

    # -- insert (ref ShuffleRepartitioner::insert_batch, shuffle/mod.rs:55)
    def insert_batch(self, batch: ColumnBatch) -> None:
        batch = batch.compact()
        if batch.num_rows == 0:
            return
        current_task().check_running()
        if self.partitioning.num_partitions == 1:
            if self._stream_sink is not None:
                self._stream_write(batch.to_arrow())
            else:
                self._stage(batch.to_arrow())
            return
        pids = self.partitioning.partition_ids(batch)
        rb = batch.to_arrow()
        arrays = [pa.array(pids, type=pa.int32())] + list(rb.columns)
        staged = pa.RecordBatch.from_arrays(
            arrays, names=["__pid"] + list(rb.schema.names))
        self._stage(staged)

    def insert_arrow(self, rb) -> None:
        """Arrow-resident insert: with ONE reduce partition no partition
        ids are needed at all — the batch stages as-is (partition-id
        work and the ColumnBatch round trip both vanish); multi-partition
        falls back through ColumnBatch for the device pid kernel."""
        if rb.num_rows == 0:
            return
        if self.partitioning.num_partitions == 1:
            current_task().check_running()
            if self._stream_sink is not None:
                self._stream_write(rb)
            elif isinstance(rb, pa.Table):
                for piece in rb.to_batches():
                    if piece.num_rows:
                        self._stage(piece)
            else:
                self._stage(rb)
            return
        if isinstance(rb, pa.Table):
            rb = rb.combine_chunks().to_batches()[0]
        self.insert_batch(ColumnBatch.from_arrow(rb))

    def _stage(self, staged) -> None:
        self._staged.append(staged)
        self._staged_bytes += staged.nbytes
        self.update_mem_used(self._staged_bytes)

    # -- spill (MemConsumer) -----------------------------------------------
    def spill(self) -> int:
        if not self._staged:
            return 0
        # spills keep the wire codec (not the local-file codec): spilled
        # frames are copied verbatim into whatever sink write()/write_rss
        # merges them into, which for RSS is a network push
        spill = _PartitionedSpill()
        with open(spill.path, "wb") as f:
            spill.offsets = self._write_partitioned(f)
        self._spills.append(spill)
        released = self._staged_bytes
        self._staged = []
        self._staged_bytes = 0
        self._mem_used = 0
        if self._metrics is not None:
            self._metrics.add("spill_count")
            self._metrics.add("spilled_bytes", released)
        return released

    def _write_partitioned(self, sink: BinaryIO,
                           codec_name: Optional[str] = None) -> List[int]:
        """Sort staged rows by pid, write per-partition frames; returns
        cumulative offsets (n+1).

        `codec_name` overrides the frame codec for staged rows headed to
        a LOCAL .data file: page-cache-backed disk where compression
        costs CPU on the critical path and saves nothing, so
        `auron.tpu.shuffle.localFileCodec` (default raw) applies there.
        Frames are self-describing (leading codec byte), so readers —
        including remote fetchers — handle any mix; set the conf to lz4
        for deployments where .data segments ship over the network more
        often than they are read back locally.  Spill frames and RSS
        pushes keep the io.compression.codec wire codec (spills may be
        merged verbatim into an RSS push, shuffle/rss.rs analog)."""
        n_parts = self.partitioning.num_partitions
        if n_parts == 1:
            # single reduce partition: every row is partition 0 — the
            # insert paths stage batches WITHOUT a __pid column here, so
            # they stream out as-is (no pid sort/take, no column strip)
            w = IpcCompressionWriter(sink, codec_name=codec_name)
            for staged in self._staged:
                w.write_batch(staged)
            w.finish()
            return [0, sink.tell()]
        tbl = pa.Table.from_batches(self._staged).combine_chunks()
        rb = tbl.to_batches()[0]
        pids = np.asarray(rb.column(0))
        from blaze_tpu.kernels import lane as lane_mod
        from blaze_tpu.kernels import radix
        lane = lane_mod.resolve("partition")
        if lane in ("pallas", "interpret") and \
                radix.vmem_estimate(len(pids), n_parts) \
                > lane_mod.vmem_budget():
            lane_mod.decline("partition", "vmem")
            lane = "scatter"
        if lane in ("pallas", "interpret"):
            # radix kernel lane: rank walk in row order — bit-identical
            # to the stable argsort grouping below
            order, starts, ends = radix.partition_order(
                pids, n_parts, interpret=(lane == "interpret"))
        elif n_parts <= 32:
            # counting sort: one flatnonzero sweep per partition beats a
            # generic argsort ~5x at small reducer counts (pids are a
            # handful of distinct values, the classic radix-1 case);
            # each sweep is a full pass over pids, so high reducer
            # counts stay on the single argsort below
            groups = [np.flatnonzero(pids == p) for p in range(n_parts)]
            order = np.concatenate(groups)
            counts = np.array([len(g) for g in groups])
            ends = counts.cumsum()
            starts = ends - counts
        else:
            order = np.argsort(pids, kind="stable")
            sorted_pids = pids[order]
            starts = np.searchsorted(sorted_pids, np.arange(n_parts),
                                     "left")
            ends = np.searchsorted(sorted_pids, np.arange(n_parts),
                                   "right")
        sorted_rb = rb.take(pa.array(order, type=pa.int64()))
        payload = sorted_rb.select(range(1, sorted_rb.num_columns))
        offsets = [0]
        bs = config.BATCH_SIZE.get()
        for p in range(n_parts):
            s, e = int(starts[p]), int(ends[p])
            if e > s:
                w = IpcCompressionWriter(sink, codec_name=codec_name)
                for off in range(s, e, bs):
                    w.write_batch(payload.slice(off, min(bs, e - off)))
                w.finish()
            offsets.append(sink.tell())
        return offsets

    # -- final write (ref shuffle_write, shuffle/mod.rs:58) ----------------
    def write(self, data_file: str, index_file: str) -> List[int]:
        """Merge spills + staged rows into .data/.index; returns lengths.

        Every mode serializes into a task-private temp file and commits
        with os.replace — a failure mid-write can never leave a
        truncated .data at the final path (the AuronShuffleWriterBase
        tmp-file discipline); the .index is written only after the
        commit, from one shared tail."""
        if self._stream_sink is not None:
            # streaming mode: frames are already on the temp file
            assert data_file == self._stream_file
            if self._stream_writer is not None:
                self._stream_writer.finish()
            end = self._stream_sink.tell()
            self._stream_sink.close()
            self._stream_sink = None
            self._stream_writer = None
            offsets = [0, end]
            os.replace(self._stream_tmp, data_file)
        else:
            tmp = f"{data_file}.inprogress.{os.getpid()}.{id(self):x}"
            try:
                with open(tmp, "wb") as out:
                    if not self._spills:
                        # no spills: partition-major frames stream
                        # straight out — BytesIO staging existed only to
                        # merge with spill segments, and doubled every
                        # shuffle byte
                        if self._staged:
                            offsets = self._write_partitioned(
                                out,
                                codec_name=config.SHUFFLE_FILE_CODEC.get())
                        else:  # empty input: empty .data, zero offsets
                            offsets = [0] * (
                                self.partitioning.num_partitions + 1)
                    else:
                        offsets = self._merge_spills_into(out)
                self._staged = []
                self._staged_bytes = 0
                self.update_mem_used(0)
                os.replace(tmp, data_file)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        with open(index_file, "wb") as idx:
            for off in offsets:
                idx.write(struct.pack("<q", off))
        # attempt-suffixed paths (speculation): first-wins promotion of
        # the index to the canonical path; a losing attempt's files are
        # discarded here and the task still returns normally — the wave
        # loop already took the winner's result
        promote_attempt_output(data_file, index_file)
        return [offsets[i + 1] - offsets[i]
                for i in range(len(offsets) - 1)]

    def _merge_spills_into(self, out: BinaryIO) -> List[int]:
        """Staged rows + spill segments, partition-major, into `out`."""
        mem_offsets: List[int] = []
        mem_buf = io.BytesIO()
        if self._staged:
            mem_offsets = self._write_partitioned(
                mem_buf, codec_name=config.SHUFFLE_FILE_CODEC.get())
        n_parts = self.partitioning.num_partitions
        offsets = [0]
        spill_files = [open(s.path, "rb") for s in self._spills]
        try:
            mem_view = mem_buf.getbuffer()
            for p in range(n_parts):
                if mem_offsets:
                    out.write(mem_view[mem_offsets[p]:mem_offsets[p + 1]])
                for s, f in zip(self._spills, spill_files):
                    seg_len = s.offsets[p + 1] - s.offsets[p]
                    if seg_len:
                        f.seek(s.offsets[p])
                        out.write(f.read(seg_len))
                offsets.append(out.tell())
        finally:
            for f in spill_files:
                f.close()
            for s in self._spills:
                s.release()
            self._spills = []
        return offsets

    def write_rss(self, rss_write: Callable[[int, bytes], None]) -> None:
        """Push per-partition bytes through a host callback
        (ref rss_shuffle_writer_exec.rs + shuffle/rss.rs:45 RssWriter)."""
        mem_offsets: List[int] = []
        mem_buf = io.BytesIO()
        if self._staged:
            mem_offsets = self._write_partitioned(mem_buf)
            self._staged = []
            self._staged_bytes = 0
            self.update_mem_used(0)
        n_parts = self.partitioning.num_partitions
        spill_files = [open(s.path, "rb") for s in self._spills]
        try:
            mem_view = mem_buf.getbuffer()
            for p in range(n_parts):
                chunks = []
                if mem_offsets:
                    chunks.append(bytes(mem_view[mem_offsets[p]:mem_offsets[p + 1]]))
                for s, f in zip(self._spills, spill_files):
                    seg_len = s.offsets[p + 1] - s.offsets[p]
                    if seg_len:
                        f.seek(s.offsets[p])
                        chunks.append(f.read(seg_len))
                data = b"".join(chunks)
                if data:
                    rss_write(p, data)
        finally:
            for f in spill_files:
                f.close()
            for s in self._spills:
                s.release()
            self._spills = []


class ShuffleWriterExec(ExecutionPlan):
    """Map-side shuffle write (ref shuffle_writer_exec.rs).  Consumes the
    child partition, writes `.data`/`.index`, emits nothing — the engine
    reads the index for MapStatus (AuronShuffleWriterBase.scala:68-85)."""

    def __init__(self, child: ExecutionPlan, partitioning: Partitioning,
                 data_file: str, index_file: str):
        super().__init__([child])
        self.partitioning = partitioning
        self.data_file = data_file
        self.index_file = index_file
        self.partition_lengths: Optional[List[int]] = None

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self, partition: int) -> BatchIterator:
        rep = ShuffleRepartitioner(self.partitioning, self.schema,
                                   self.metrics)
        rep.set_spillable(MemManager.get())
        child = self.children[0]
        # single-partition writes take the Arrow-resident insert (no
        # partition ids needed) when the child natively produces Arrow;
        # multi-partition keeps ColumnBatch — partition ids come from the
        # device pid kernel, and round-tripping Arrow through
        # insert_arrow would ADD conversions for device-resident children
        arrow_native = (self.partitioning.num_partitions == 1
                        and type(child).arrow_batches
                        is not ExecutionPlan.arrow_batches)
        try:
            # single-reduce local writes stream frames to disk as
            # they arrive (compute/IO overlap, no staging hump)
            rep.open_stream(self.data_file)
            # sinks yield nothing, so the stream meter never sees rows;
            # count what is written (rows in == rows shuffled out).
            # the child stream pulls on a prefetch worker so upstream
            # compute overlaps this map task's partition/write IO
            from blaze_tpu.ops.base import prefetch
            if arrow_native:
                for rb in prefetch(child.arrow_batches(partition),
                                   name="shuffle_map"):
                    self.metrics.add("output_rows", rb.num_rows)
                    self.metrics.add("output_batches")
                    rep.insert_arrow(rb)
            else:
                for batch in prefetch(child.execute(partition),
                                      name="shuffle_map"):
                    self.metrics.add("output_rows", batch.num_rows)
                    self.metrics.add("output_batches")
                    rep.insert_batch(batch)
            self.partition_lengths = rep.write(self.data_file,
                                               self.index_file)
            self.metrics.add("data_size", sum(self.partition_lengths))
            self.metrics.add("io_bytes", sum(self.partition_lengths))
        finally:
            rep.close()
            rep.unregister()
        return iter(())


class RssShuffleWriterExec(ExecutionPlan):
    """Remote-shuffle-service writer: bytes go through a callback instead of
    local files (ref rss_shuffle_writer_exec.rs)."""

    def __init__(self, child: ExecutionPlan, partitioning: Partitioning,
                 rss_write: Callable[[int, bytes], None]):
        super().__init__([child])
        self.partitioning = partitioning
        self._rss_write = rss_write

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self, partition: int) -> BatchIterator:
        rep = ShuffleRepartitioner(self.partitioning, self.schema,
                                   self.metrics)
        rep.set_spillable(MemManager.get())
        try:
            for batch in self.children[0].execute(partition):
                self.metrics.add("output_rows", batch.num_rows)
                self.metrics.add("output_batches")
                rep.insert_batch(batch)
            rep.write_rss(self._rss_write)
        finally:
            rep.unregister()
        return iter(())
