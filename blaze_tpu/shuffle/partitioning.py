"""Partitioning schemes: hash / round-robin / single / range.

Parity: shuffle/mod.rs:113-123 (Partitioning enum) and the Spark-compatible
partition id computation `pmod(murmur3(cols, seed=42), n)`
(ref shuffle/mod.rs:164-189) — bit-exact with Spark's HashPartitioning so a
native map stage can feed vanilla Spark reducers and vice versa.  Range
partitioning uses driver-sampled bounds rows compared via the same host
order-key encoding as sort (ref NativeShuffleExchangeBase.scala:313
rangePartitioningBound + evaluate_range_partition_ids).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from blaze_tpu.batch import ColumnBatch
from blaze_tpu.bridge.context import current_task
from blaze_tpu.exprs import PhysicalExpr
from blaze_tpu.kernels import hashing as H


class Partitioning:
    num_partitions: int = 1

    def partition_ids(self, batch: ColumnBatch) -> np.ndarray:
        """int32 partition id per (selected) row; batch must be compact."""
        raise NotImplementedError


@dataclass
class SinglePartitioning(Partitioning):
    num_partitions: int = 1

    def partition_ids(self, batch: ColumnBatch) -> np.ndarray:
        return np.zeros(batch.num_rows, dtype=np.int32)


# One compiled kernel per (column type signature, partition count): the
# murmur3 chain is ~100 elementwise primitives; dispatched eagerly they
# dominate the whole shuffle write (profiled at ~80% of q01 map wall).
import functools


@functools.lru_cache(maxsize=128)
def _hash_pmod_jit(tids: Tuple[str, ...], n_parts: int):
    def f(flat_cols):
        # the ONE shared pid definition (normalization included) —
        # identical to the device collective lane and the host path
        return H.spark_partition_ids(flat_cols, tids, n_parts, xp=jnp)
    from blaze_tpu.bridge.xla_stats import meter_jit
    return meter_jit(f, name="shuffle.hash_pmod")


def _native_pmod(flat_cols, tids, n_parts):
    """Fused murmur3+pmod through the native partition kernel
    (partition_kernel.cpp) for all-fixed-width keys; None -> numpy
    chain (strings, unbuilt lib).  Caller has already normalized float
    keys, so every NaN carries the canonical bit pattern the bits-view
    below hashes."""
    import ctypes

    from blaze_tpu.bridge.native import get_partition_kernel
    lib = get_partition_kernel()
    if lib is None:
        return None
    _SUPPORTED = ("bool", "int8", "int16", "int32", "date32", "int64",
                  "timestamp_us", "decimal", "float32", "float64")
    if any(tid not in _SUPPORTED for tid in tids):
        return None  # pre-scan: don't convert columns only to bail
    modes = []
    datas = []      # keeps converted arrays alive across the call
    valid_nps = []
    n = None
    for (v, val), tid in zip(flat_cols, tids):
        if tid in ("bool", "int8", "int16", "int32", "date32"):
            modes.append(0)
            datas.append(np.ascontiguousarray(v, dtype=np.int32))
        elif tid in ("int64", "timestamp_us", "decimal"):
            modes.append(1)
            datas.append(np.ascontiguousarray(v, dtype=np.int64))
        elif tid == "float32":
            modes.append(0)
            datas.append(np.ascontiguousarray(
                v, dtype=np.float32).view(np.int32))
        elif tid == "float64":
            modes.append(1)
            datas.append(np.ascontiguousarray(
                v, dtype=np.float64).view(np.int64))
        else:
            return None  # utf8/binary: numpy byte-matrix path
        n = len(datas[-1]) if n is None else n
        valid_nps.append(
            None if val is None or bool(np.all(val))
            else np.ascontiguousarray(val, dtype=np.uint8))
    if n is None:
        return None
    out = np.empty(n, dtype=np.int32)

    def ptr(a):
        return ctypes.c_void_p(a.ctypes.data) if a is not None else None

    nc = len(modes)
    rc = lib.blaze_murmur3_pmod(
        n, nc, (ctypes.c_int32 * nc)(*modes),
        (ctypes.c_void_p * nc)(*[ptr(a) for a in datas]),
        (ctypes.c_void_p * nc)(*[ptr(a) for a in valid_nps]),
        n_parts, ptr(out))
    return out if rc == 0 else None


class HashPartitioning(Partitioning):
    def __init__(self, exprs: Sequence[PhysicalExpr], num_partitions: int):
        self.exprs = list(exprs)
        self.num_partitions = num_partitions

    def partition_ids(self, batch: ColumnBatch) -> np.ndarray:
        from blaze_tpu.bridge.placement import host_resident
        from blaze_tpu.xputil import asnp
        n = batch.num_rows
        if self.num_partitions == 1:
            # pmod(h, 1) == 0 for every row: skip the hash chain
            return np.zeros(n, dtype=np.int32)
        on_host = host_resident()
        # host batches are unpadded; hashing in numpy avoids one jit
        # compile per distinct tail-batch length
        cap = n if on_host else batch.capacity
        flat_cols = []
        tids = []
        for e in self.exprs:
            v = e.evaluate(batch)
            if v.is_device:
                if on_host:
                    flat_cols.append((asnp(v.data)[:cap],
                                      asnp(v.validity)[:cap]))
                else:
                    flat_cols.append((v.data, v.validity))
                tids.append(v.dtype.id.value)
            else:
                # host (string) columns are exact-length; pad the byte
                # matrix to the batch capacity so mixed string+fixed key
                # hashes line up lane-for-lane
                arr = v.to_host(n)
                (mat, lengths), valid = H.string_column_to_padded_bytes(arr)
                # pow2 width bucket: one compile per bucket, not per batch
                w = max(4, 1 << (mat.shape[1] - 1).bit_length()) \
                    if mat.shape[1] else 4
                full = np.zeros((cap, w), dtype=mat.dtype)
                full[:mat.shape[0], :mat.shape[1]] = mat
                full_len = np.zeros(cap, dtype=lengths.dtype)
                full_len[:len(lengths)] = lengths
                pad_valid = np.zeros(cap, dtype=bool)
                pad_valid[:len(valid)] = valid
                if on_host:
                    flat_cols.append(((full, full_len), pad_valid))
                else:
                    flat_cols.append(((jnp.asarray(full),
                                       jnp.asarray(full_len)),
                                      jnp.asarray(pad_valid)))
                tids.append("utf8")
        if on_host:
            # the native kernel hashes raw bit views, so it needs the
            # normalization applied up front; the numpy fallback goes
            # through the shared definition (normalization idempotent)
            flat_cols = H.norm_float_keys(flat_cols, tids, np)
            pids = _native_pmod(flat_cols, tids, self.num_partitions)
            if pids is not None:
                return pids[:n]
            pids = H.spark_partition_ids(flat_cols, tids,
                                         self.num_partitions, xp=np)
            return np.asarray(pids)[:n].astype(np.int32)
        pids = _hash_pmod_jit(tuple(tids), self.num_partitions)(flat_cols)
        return np.asarray(pids)[:n].astype(np.int32)


class RoundRobinPartitioning(Partitioning):
    def __init__(self, num_partitions: int):
        self.num_partitions = num_partitions
        self._next = 0

    def partition_ids(self, batch: ColumnBatch) -> np.ndarray:
        n = batch.num_rows
        # Spark RoundRobin starts at a per-task position; keep a running
        # cursor so rows spread evenly across batches
        ids = (np.arange(n, dtype=np.int64) + self._next) % self.num_partitions
        self._next = int((self._next + n) % self.num_partitions)
        return ids.astype(np.int32)


class RangePartitioning(Partitioning):
    """Bounds rows (one per cut, sorted) decide the partition id via
    binary search on host order keys."""

    def __init__(self, sort_exprs: Sequence[Tuple[PhysicalExpr, bool, bool]],
                 num_partitions: int, bounds: pa.RecordBatch):
        self.sort_exprs = list(sort_exprs)
        self.num_partitions = num_partitions
        self.bounds = bounds  # num_partitions-1 rows, columns match sort keys
        from blaze_tpu.ops.sort import host_sort_keys
        self._bound_keys = host_sort_keys(
            bounds, list(range(bounds.num_columns)),
            [d for _, d, _ in self.sort_exprs],
            [f for _, _, f in self.sort_exprs])

    def partition_ids(self, batch: ColumnBatch) -> np.ndarray:
        from blaze_tpu.ops.sort import host_sort_keys
        n = batch.num_rows
        arrays = [e.evaluate(batch).to_host(n)
                  for e, _, _ in self.sort_exprs]
        rb = pa.RecordBatch.from_arrays(
            arrays, names=[f"k{i}" for i in range(len(arrays))])
        row_keys = host_sort_keys(rb, list(range(len(arrays))),
                                  [d for _, d, _ in self.sort_exprs],
                                  [f for _, _, f in self.sort_exprs])
        # id = count of bounds STRICTLY below the row (ties stay in the
        # bound's own partition, matching Spark RangePartitioner)
        nb = len(self._bound_keys[0])
        ids = np.zeros(n, dtype=np.int32)
        from blaze_tpu.ops.sort import compare_scalar
        for b in range(nb):
            gt = np.zeros(n, dtype=bool)
            for j in range(len(row_keys) - 1, -1, -1):
                rk = row_keys[j]
                bk = compare_scalar(rk, self._bound_keys[j][b])
                gt = (rk > bk) | ((rk == bk) & gt)
            ids += gt.astype(np.int32)
        return ids


def sample_range_bounds(sample: pa.Table,
                        sort_exprs: Sequence[Tuple[PhysicalExpr, bool, bool]],
                        num_partitions: int,
                        key_names: Sequence[str]) -> pa.RecordBatch:
    """Driver-side bounds sampling (the rangePartitioningBound analog):
    sort the sample, pick num_partitions-1 evenly spaced rows."""
    from blaze_tpu.ops import MemoryScanExec, SortExec
    scan = MemoryScanExec.from_arrow(sample)
    plan = SortExec(scan, sort_exprs)
    sorted_rb = plan.execute_collect().to_arrow()
    n = sorted_rb.num_rows
    cuts = [int(n * (i + 1) / num_partitions) for i in range(num_partitions - 1)]
    cuts = [min(c, n - 1) for c in cuts]
    idx = pa.array(cuts, type=pa.int64())
    cols = [sorted_rb.column(sorted_rb.schema.get_field_index(k)).take(idx)
            for k in key_names]
    return pa.RecordBatch.from_arrays(cols, names=list(key_names))
