"""ORC scan.

Parity: orc_exec.rs (1,647 LoC orc-rust scan with the same FS bridge and
schema-evolution confs) — pyarrow's C++ ORC reader plays the native-decoder
role; positional vs by-name column matching mirrors
`auron.orc.force.positional.evolution`.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import pyarrow as pa

from blaze_tpu import config
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.ops.base import BatchIterator, ExecutionPlan
from blaze_tpu.ops.scan import _align_schema
from blaze_tpu.schema import Schema

ORC_FORCE_POSITIONAL = config.ORC_FORCE_POSITIONAL_EVOLUTION


class OrcScanExec(ExecutionPlan):

    def __init__(self, schema: Schema, file_groups: Sequence[Sequence[str]],
                 projection: Optional[Sequence[str]] = None,
                 batch_rows: Optional[int] = None):
        super().__init__()
        self._file_schema = schema
        self._projection = list(projection) if projection is not None else None
        self._schema = (Schema([schema.field(n) for n in self._projection])
                        if self._projection is not None else schema)
        self._file_groups = [list(g) for g in file_groups]
        self._batch_rows = batch_rows or config.BATCH_SIZE.get()

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def num_partitions(self) -> int:
        return len(self._file_groups)

    def execute(self, partition: int) -> BatchIterator:
        from pyarrow import orc
        positional = ORC_FORCE_POSITIONAL.get()
        for path in self._file_groups[partition]:
            try:
                f = orc.ORCFile(path)
            except Exception:
                if config.IGNORE_CORRUPTED_FILES.get():
                    continue
                raise
            file_names = list(f.schema.names)
            if positional and self._projection is not None:
                # hive-style positional evolution: physical names are
                # ignored, the file's column AT THE DECLARED POSITION
                # serves each projected column (ref orc_exec.rs
                # force_positional_evolution).  Only the needed
                # positions decode — column pruning survives.
                idx = [self._file_schema.index_of(n)
                       for n in self._projection]
                keep = sorted({i for i in idx if i < len(file_names)})
                if keep:
                    # pyarrow returns requested columns in FILE order and
                    # collapses duplicates — select per projected position
                    # from the result instead of trusting request order
                    read = f.read(columns=[file_names[i] for i in keep])
                    table = pa.table(
                        {self._projection[k]: read.column(file_names[i])
                         for k, i in enumerate(idx)
                         if i < len(file_names)})
                else:
                    table = None
            else:
                # by-name evolution: columns added to the table after
                # this file was written are absent here — _align_schema
                # below null-fills them (ref schema_adapter semantics)
                present = (None if self._projection is None else
                           [n for n in self._projection
                            if n in set(file_names)])
                table = (f.read(columns=present)
                         if present is None or present else None)
            if table is None:
                # no projected column exists in this old file: the rows
                # still exist — emit all-null rows (f.read(columns=[])
                # would return ZERO rows and silently drop them)
                table = pa.table(
                    {n: pa.nulls(f.nrows,
                                 self._schema.field(n).data_type
                                 .to_arrow())
                     for n in self._schema.names})
            for rb in table.to_batches(max_chunksize=self._batch_rows):
                rb = _align_schema(rb, self._schema)
                cb = ColumnBatch.from_arrow(rb)
                self.metrics.add("output_rows", cb.num_rows)
                yield cb
