"""ORC scan.

Parity: orc_exec.rs (1,647 LoC orc-rust scan with the same FS bridge and
schema-evolution confs) — pyarrow's C++ ORC reader plays the native-decoder
role; positional vs by-name column matching mirrors
`auron.orc.force.positional.evolution`.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import pyarrow as pa

from blaze_tpu import config
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.ops.base import BatchIterator, ExecutionPlan
from blaze_tpu.ops.scan import _align_schema
from blaze_tpu.schema import Schema

ORC_FORCE_POSITIONAL = config.ORC_FORCE_POSITIONAL_EVOLUTION


class OrcScanExec(ExecutionPlan):

    def __init__(self, schema: Schema, file_groups: Sequence[Sequence[str]],
                 projection: Optional[Sequence[str]] = None,
                 batch_rows: Optional[int] = None):
        super().__init__()
        self._file_schema = schema
        self._projection = list(projection) if projection is not None else None
        self._schema = (Schema([schema.field(n) for n in self._projection])
                        if self._projection is not None else schema)
        self._file_groups = [list(g) for g in file_groups]
        self._batch_rows = batch_rows or config.BATCH_SIZE.get()

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def num_partitions(self) -> int:
        return len(self._file_groups)

    def execute(self, partition: int) -> BatchIterator:
        from pyarrow import orc
        positional = ORC_FORCE_POSITIONAL.get()
        for path in self._file_groups[partition]:
            try:
                f = orc.ORCFile(path)
            except Exception:
                if config.IGNORE_CORRUPTED_FILES.get():
                    continue
                raise
            table = f.read(columns=self._projection
                           if not positional else None)
            if positional and self._projection is not None:
                idx = [self._file_schema.index_of(n)
                       for n in self._projection]
                table = table.select(idx)
            for rb in table.to_batches(max_chunksize=self._batch_rows):
                rb = _align_schema(rb, self._schema)
                cb = ColumnBatch.from_arrow(rb)
                self.metrics.add("output_rows", cb.num_rows)
                yield cb
