"""ORC scan.

Parity: orc_exec.rs (1,647 LoC orc-rust scan) — pyarrow's C++ ORC
reader plays the native-decoder role:

  * STRIPE-granular streaming (`execute_orc_scan` polls one record
    batch at a time; whole-file materialization would defeat the
    memory budget on big files),
  * the engine FS bridge for scheme'd paths (OrcFileReaderRef over
    `get_bytes`/hadoop-fs — here `open_source`, the same object the
    parquet scan reads through),
  * positional vs by-name schema evolution mirroring
    `auron.orc.force.positional.evolution` (SchemaAdapter),
  * Hive partition-constant columns appended per file
    (FileScanConfig partition_values), enabling partitioned Hive ORC
    tables through the converter,
  * cooperative cancellation between stripes (is_task_running poll).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import pyarrow as pa

from blaze_tpu import config
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.bridge.context import current_task
from blaze_tpu.ops.base import BatchIterator, ExecutionPlan
from blaze_tpu.ops.scan import (_align_schema,
                                assemble_partition_constants,
                                open_source)
from blaze_tpu.schema import Field, Schema

ORC_FORCE_POSITIONAL = config.ORC_FORCE_POSITIONAL_EVOLUTION


class OrcScanExec(ExecutionPlan):

    def __init__(self, schema: Schema, file_groups: Sequence[Sequence[str]],
                 projection: Optional[Sequence[str]] = None,
                 batch_rows: Optional[int] = None,
                 partition_schema: Optional[Schema] = None,
                 partition_values: Optional[Sequence[Sequence[Sequence]]]
                 = None):
        super().__init__()
        self._file_schema = schema
        self._file_groups = [list(g) for g in file_groups]
        self._partition_schema = partition_schema
        self._partition_values = partition_values  # [group][file][field]
        part_names = ({f.name for f in partition_schema}
                      if partition_schema is not None else set())
        self._projection = list(projection) if projection is not None else None
        if self._projection is not None:
            self._file_projection: Optional[List[str]] = [
                n for n in self._projection if n not in part_names]
            out_fields: List[Field] = []
            for n in self._projection:
                out_fields.append(
                    partition_schema.field(n) if n in part_names
                    else schema.field(n))
            self._schema = Schema(out_fields)
        else:
            self._file_projection = None
            fields = list(schema)
            if partition_schema is not None:
                fields += list(partition_schema)
            self._schema = Schema(fields)
        self._batch_rows = batch_rows or config.BATCH_SIZE.get()

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def num_partitions(self) -> int:
        return len(self._file_groups)

    def execute(self, partition: int) -> BatchIterator:
        ctx = current_task()
        for fidx, path in enumerate(self._file_groups[partition]):
            from pyarrow import orc
            try:
                f = orc.ORCFile(open_source(path))
            except Exception:
                if config.IGNORE_CORRUPTED_FILES.get():
                    continue
                raise
            # stripe-granular poll: bounded memory + a cancellation
            # point per stripe (orc_exec.rs polls the stream likewise).
            # nstripes == 0 (empty writer output) emits nothing — a
            # forced stripe-0 read would raise Out of bounds
            for stripe in range(f.nstripes):
                ctx.check_running()
                tbl = self._read_stripe(f, stripe)
                if tbl is None or tbl.num_rows == 0:
                    continue
                self.metrics.add("io_bytes", tbl.nbytes)
                for rb in tbl.to_batches(max_chunksize=self._batch_rows):
                    if self._partition_schema is not None:
                        rb = assemble_partition_constants(
                            rb, self._schema, self._partition_schema,
                            self._partition_values, partition, fidx)
                    rb = _align_schema(rb, self._schema)
                    yield ColumnBatch.from_arrow(rb)
            del f  # drop the reader (and any FS-bridge handle) eagerly

    # ------------------------------------------------------------------
    def _read_stripe(self, f, stripe: int) -> Optional[pa.Table]:
        file_names = list(f.schema.names)
        positional = ORC_FORCE_POSITIONAL.get()
        proj = self._file_projection
        if positional and proj is not None:
            # hive-style positional evolution: physical names are
            # ignored, the file's column AT THE DECLARED POSITION
            # serves each projected column (ref orc_exec.rs
            # force_positional_evolution).  Only needed positions decode.
            idx = [self._file_schema.index_of(n) for n in proj]
            keep = sorted({i for i in idx if i < len(file_names)})
            if keep:
                read = pa.Table.from_batches([f.read_stripe(
                    stripe, columns=[file_names[i] for i in keep])])
                return pa.table(
                    {proj[k]: read.column(file_names[i])
                     for k, i in enumerate(idx) if i < len(file_names)})
            return self._null_rows(f, stripe, proj)
        # by-name evolution: columns added after this file was written
        # are absent — _align_schema null-fills them (schema_adapter)
        present = (None if proj is None else
                   [n for n in proj if n in set(file_names)])
        if present is None or present:
            return pa.Table.from_batches(
                [f.read_stripe(stripe, columns=present)])
        return self._null_rows(f, stripe, proj)

    def _null_rows(self, f, stripe: int, proj) -> Optional[pa.Table]:
        """No projected column exists in this old file: the rows still
        exist — emit all-null rows instead of silently dropping them.
        Row counts must come from a real column (columns=[] reads back
        zero rows), so decode the cheapest one: the first FIXED-WIDTH
        physical column when any exists (a wide string column would
        decompress megabytes just for num_rows)."""
        file_names = list(f.schema.names)
        if file_names:
            pick = file_names[0]
            for name, t in zip(file_names, f.schema.types):
                if pa.types.is_primitive(t):
                    pick = name
                    break
            n_rows = f.read_stripe(stripe, columns=[pick]).num_rows
        else:
            if stripe > 0:
                return None
            n_rows = f.nrows
        return pa.table(
            {n: pa.nulls(n_rows,
                         self._file_schema.field(n).data_type.to_arrow())
             for n in (proj or self._file_schema.names)})

