"""Kafka scan (Flink front-end path) + mock variant + deserializers.

Parity: datafusion-ext-plans/src/flink/kafka_scan_exec.rs:81 (native Kafka
consumer via rdkafka), kafka_mock_scan_exec.rs (broker-less test variant),
and flink/serde/{json,pb}_deserializer.rs (record bytes -> columns).

No Kafka client library ships in this environment, so the real consumer is
gated behind a host-registered poll callback (the same inversion the
reference uses for its JVM-backed sources), while MockKafkaScanExec serves
framed records from memory — the unit-test path the reference also ships.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Callable, Iterator, List, Optional, Sequence

import numpy as np
import pyarrow as pa

from blaze_tpu import config
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.ops.base import BatchIterator, ExecutionPlan
from blaze_tpu.schema import DataType, Field, INT64, Schema, TypeId


class RecordDeserializer:
    """bytes records -> arrow arrays matching the scan schema."""

    def __init__(self, schema: Schema):
        self.schema = schema

    def deserialize(self, records: List[Optional[bytes]]) -> pa.RecordBatch:
        raise NotImplementedError


class JsonDeserializer(RecordDeserializer):
    """(ref flink/serde/json_deserializer.rs — 1,091 LoC): JSON object per
    record; missing/invalid fields -> null (non-strict mode)."""

    def deserialize(self, records: List[Optional[bytes]]) -> pa.RecordBatch:
        cols: List[List] = [[] for _ in self.schema]
        for rec in records:
            doc = None
            if rec is not None:
                try:
                    doc = json.loads(rec)
                except (ValueError, UnicodeDecodeError):
                    doc = None
            for i, f in enumerate(self.schema):
                v = doc.get(f.name) if isinstance(doc, dict) else None
                cols[i].append(_coerce_json(v, f.data_type))
        arrays = [pa.array(c, type=f.data_type.to_arrow())
                  for c, f in zip(cols, self.schema)]
        return pa.RecordBatch.from_arrays(arrays,
                                          schema=self.schema.to_arrow())


def _coerce_json(v, t: DataType):
    if v is None:
        return None
    try:
        if t.is_integer:
            return int(v)
        if t.is_floating:
            return float(v)
        if t.id == TypeId.BOOL:
            return bool(v)
        if t.id == TypeId.UTF8:
            return v if isinstance(v, str) else json.dumps(v)
        return v
    except (ValueError, TypeError):
        return None


class PbDeserializer(RecordDeserializer):
    """(ref flink/serde/pb_deserializer.rs — 2,836 LoC): length-prefixed
    protobuf messages decoded through a host-supplied message factory
    (google.protobuf is available; the schema descriptor comes from the
    engine side, as in the reference's descriptor-set handshake)."""

    def __init__(self, schema: Schema, message_factory: Callable):
        super().__init__(schema)
        self._factory = message_factory

    def deserialize(self, records: List[Optional[bytes]]) -> pa.RecordBatch:
        cols: List[List] = [[] for _ in self.schema]
        for rec in records:
            msg = None
            if rec is not None:
                try:
                    msg = self._factory()
                    msg.ParseFromString(rec)
                except Exception:
                    msg = None
            for i, f in enumerate(self.schema):
                v = getattr(msg, f.name, None) if msg is not None else None
                cols[i].append(_coerce_json(v, f.data_type))
        arrays = [pa.array(c, type=f.data_type.to_arrow())
                  for c, f in zip(cols, self.schema)]
        return pa.RecordBatch.from_arrays(arrays,
                                          schema=self.schema.to_arrow())


@dataclass
class KafkaRecord:
    value: Optional[bytes]
    key: Optional[bytes] = None
    offset: int = 0
    partition: int = 0
    timestamp_ms: int = 0


def schema_with_event_time(schema: Schema,
                           event_time_field: Optional[str]) -> Schema:
    """Scan output schema when record timestamps are surfaced: the
    deserialized columns plus one int64 event-time column (epoch ms,
    from KafkaRecord.timestamp_ms — Flink's StreamRecord timestamp)."""
    if not event_time_field:
        return schema
    if event_time_field in schema.names:
        raise ValueError(
            f"event-time field {event_time_field!r} collides with a "
            "deserialized column")
    return Schema(list(schema) + [Field(event_time_field, INT64, False)])


def _append_event_time(rb: pa.RecordBatch, recs: Sequence[KafkaRecord],
                       out_schema: Schema) -> pa.RecordBatch:
    ts = pa.array([int(r.timestamp_ms) for r in recs], type=pa.int64())
    return pa.RecordBatch.from_arrays(list(rb.columns) + [ts],
                                      schema=out_schema.to_arrow())


class MockKafkaScanExec(ExecutionPlan):
    """Broker-less source (ref kafka_mock_scan_exec.rs): serves pre-staged
    records, one kafka partition per plan partition."""

    def __init__(self, schema: Schema, deserializer: RecordDeserializer,
                 partitions: Sequence[Sequence[KafkaRecord]],
                 max_batches: Optional[int] = None,
                 event_time_field: Optional[str] = None):
        super().__init__()
        self._event_time_field = event_time_field
        self._schema = schema_with_event_time(schema, event_time_field)
        self._deser = deserializer
        self._partitions = [list(p) for p in partitions]

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def num_partitions(self) -> int:
        return len(self._partitions)

    def execute(self, partition: int) -> BatchIterator:
        bs = config.BATCH_SIZE.get()
        recs = self._partitions[partition]
        for off in range(0, len(recs), bs):
            chunk = recs[off:off + bs]
            rb = self._deser.deserialize([r.value for r in chunk])
            if self._event_time_field:
                rb = _append_event_time(rb, chunk, self._schema)
            self.metrics.add("io_bytes", rb.nbytes)
            yield ColumnBatch.from_arrow(rb)


class KafkaScanExec(ExecutionPlan):
    """Streaming source driven by a host-registered poll callback
    `poll(partition, max_records) -> List[KafkaRecord] | None` (None = end;
    the unbounded case is driven by the streaming runtime's checkpoints).
    """

    def __init__(self, schema: Schema, deserializer: RecordDeserializer,
                 poll_resource_id: str, num_partitions: int = 1,
                 event_time_field: Optional[str] = None):
        super().__init__()
        self._event_time_field = event_time_field
        self._schema = schema_with_event_time(schema, event_time_field)
        self._deser = deserializer
        self._poll_id = poll_resource_id
        self._n = num_partitions

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def num_partitions(self) -> int:
        return self._n

    def execute(self, partition: int) -> BatchIterator:
        from blaze_tpu.bridge.resource import get_resource
        poll = get_resource(self._poll_id)
        if poll is None:
            raise KeyError(f"kafka poll resource {self._poll_id!r}")
        bs = config.BATCH_SIZE.get()
        while True:
            recs = poll(partition, bs)
            if recs is None:
                return
            if not recs:
                continue
            rb = self._deser.deserialize([r.value for r in recs])
            if self._event_time_field:
                rb = _append_event_time(rb, recs, self._schema)
            self.metrics.add("io_bytes", rb.nbytes)
            yield ColumnBatch.from_arrow(rb)
