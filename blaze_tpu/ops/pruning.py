"""Row-group pruning with parquet min/max statistics.

Parity: the reference delegates page/row-group filtering to DataFusion's
parquet source gated by `auron.parquet.enable.pageFiltering` (ref
conf.rs:43, parquet_exec.rs).  Here: interval analysis of the filter
PhysicalExpr against per-row-group [min, max] statistics — a conservative
evaluator that returns "maybe" unless stats prove a group empty.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from blaze_tpu.exprs.base import BoundReference, Literal, PhysicalExpr
from blaze_tpu.exprs.binary import BinaryExpr
from blaze_tpu.exprs.conditional import InList, IsNotNull, IsNull
from blaze_tpu.schema import Schema

Interval = Tuple[Optional[object], Optional[object], bool]  # (min, max, has_nulls)


def _name_to_col(md):
    return {md.schema.column(i).name: i for i in range(len(md.schema))}


def pred_columns(pred: PhysicalExpr, schema: Schema) -> set:
    """Column names the predicate references (for stats extraction)."""
    out = set()
    stack = [pred]
    while stack:
        e = stack.pop()
        name = _col_name(e, schema)
        if name is not None:
            out.add(name)
        stack.extend(getattr(e, "children", lambda: ())() or ())
    return out


def _group_stats(rg, name_to_col, strict_nulls: bool) -> dict:
    """Per-column (min, max, has_nulls) for one row group.

    strict_nulls: a MISSING null_count counts as "may have nulls" — the
    always-match direction is only sound when absence of nulls is
    PROVEN; the may-match direction stays permissive."""
    stats = {}
    for name, ci in name_to_col.items():
        col = rg.column(ci)
        if col.statistics is not None and col.statistics.has_min_max:
            nc = col.statistics.null_count
            has_nulls = ((nc is None or nc > 0) if strict_nulls
                         else (nc or 0) > 0)
            stats[name] = (col.statistics.min, col.statistics.max,
                           has_nulls)
    return stats


def _pred_cols_map(md, schema: Schema, predicate: PhysicalExpr) -> dict:
    """name->column-index restricted to predicate-referenced columns —
    stats extraction cost scales with the predicate, not the schema."""
    wanted = pred_columns(predicate, schema)
    return {n: i for n, i in _name_to_col(md).items() if n in wanted}


def split_may_match(predicate: PhysicalExpr, schema: Schema,
                    constants: dict) -> bool:
    """Partition pruning for provider scans: a split whose partition
    constants (each a degenerate [v, v] interval) PROVE the predicate
    false can be dropped before any file IO.  Conservative — True
    whenever the predicate references non-partition columns."""
    stats = {k: (v, v, v is None) for k, v in constants.items()}
    return _may_match(predicate, schema, stats)


def prune_with_stats(md, schema: Schema, predicate: PhysicalExpr,
                     groups: List[int]) -> List[int]:
    name_to_col = _pred_cols_map(md, schema, predicate)
    keep = []
    for g in groups:
        stats = _group_stats(md.row_group(g), name_to_col,
                             strict_nulls=False)
        if _may_match(predicate, schema, stats):
            keep.append(g)
    return keep


def groups_always_match(md, schema: Schema, predicate: PhysicalExpr,
                        groups: List[int]) -> bool:
    """True only when stats PROVE every row of every listed group
    satisfies `predicate` — lets the caller elide the filter mask for
    fully-covered groups (the common case for a range predicate over a
    date-clustered fact table).  Conservative: False when unsure."""
    covered, _boundary = split_covered(md, schema, predicate, groups)
    return len(covered) == len(groups)


def split_covered(md, schema: Schema, predicate: PhysicalExpr,
                  groups: List[int]):
    """(covered, boundary): kept groups whose stats PROVE full predicate
    coverage (filter mask elidable) vs the rest — one metadata pass."""
    name_to_col = _pred_cols_map(md, schema, predicate)
    covered, boundary = [], []
    for g in groups:
        stats = _group_stats(md.row_group(g), name_to_col,
                             strict_nulls=True)
        (covered if _always_match(predicate, schema, stats)
         else boundary).append(g)
    return covered, boundary


def _always_match(pred: PhysicalExpr, schema: Schema, stats: dict) -> bool:
    """True only when stats prove ALL rows match (a null comparison
    evaluates null, which a filter drops, so a column with nulls in the
    group can never prove always-match)."""
    if isinstance(pred, BinaryExpr):
        if pred.op == "and":
            return (_always_match(pred.left, schema, stats) and
                    _always_match(pred.right, schema, stats))
        if pred.op == "or":
            return (_always_match(pred.left, schema, stats) or
                    _always_match(pred.right, schema, stats))
        if pred.op in ("==", "<", "<=", ">", ">="):
            name, lit, op = (_col_name(pred.left, schema),
                             _lit_value(pred.right), pred.op)
            if name is None and _col_name(pred.right, schema) is not None:
                flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=",
                        "==": "=="}
                name, lit, op = (_col_name(pred.right, schema),
                                 _lit_value(pred.left), flip[pred.op])
            if name is None or lit is None or name not in stats:
                return False
            mn, mx, has_nulls = stats[name]
            if has_nulls:
                return False
            # parquet float/double min/max statistics IGNORE NaN rows,
            # and a NaN comparison is false under the filter — floating
            # stats can never PROVE all rows match (DataFusion applies
            # the same restriction)
            if isinstance(mn, float) or isinstance(mx, float):
                return False
            try:
                if op == "==":
                    return mn == lit == mx
                if op == "<":
                    return mx < lit
                if op == "<=":
                    return mx <= lit
                if op == ">":
                    return mn > lit
                if op == ">=":
                    return mn >= lit
            except TypeError:
                return False
        return False
    if isinstance(pred, IsNotNull):
        name = _col_name(pred.child, schema)
        if name is not None and name in stats:
            return not stats[name][2]
        return False
    return False


def _col_name(expr: PhysicalExpr, schema: Schema) -> Optional[str]:
    if isinstance(expr, BoundReference):
        if expr.name:
            return expr.name
        if expr.index < len(schema):
            return schema[expr.index].name
    return None


def _lit_value(expr: PhysicalExpr):
    if isinstance(expr, Literal):
        return expr.value
    return None


def _may_match(pred: PhysicalExpr, schema: Schema, stats: dict) -> bool:
    """Conservative: False only when stats PROVE no row matches."""
    if isinstance(pred, BinaryExpr):
        if pred.op == "and":
            return (_may_match(pred.left, schema, stats) and
                    _may_match(pred.right, schema, stats))
        if pred.op == "or":
            return (_may_match(pred.left, schema, stats) or
                    _may_match(pred.right, schema, stats))
        if pred.op in ("==", "<", "<=", ">", ">="):
            # normalize to col OP lit
            name, lit, op = _col_name(pred.left, schema), _lit_value(pred.right), pred.op
            if name is None and _col_name(pred.right, schema) is not None:
                flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "==": "=="}
                name, lit, op = (_col_name(pred.right, schema),
                                 _lit_value(pred.left), flip[pred.op])
            if name is None or lit is None or name not in stats:
                return True
            mn, mx, _ = stats[name]
            try:
                if op == "==":
                    return mn <= lit <= mx
                if op == "<":
                    return mn < lit
                if op == "<=":
                    return mn <= lit
                if op == ">":
                    return mx > lit
                if op == ">=":
                    return mx >= lit
            except TypeError:
                return True
        return True
    if isinstance(pred, InList) and not pred.negated:
        name = _col_name(pred.child, schema)
        if name is None or name not in stats:
            return True
        mn, mx, _ = stats[name]
        try:
            return any(v is not None and mn <= v <= mx for v in pred.values)
        except TypeError:
            return True
    if isinstance(pred, IsNull):
        name = _col_name(pred.child, schema)
        if name is not None and name in stats:
            return stats[name][2]
        return True
    return True
