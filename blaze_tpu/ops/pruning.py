"""Row-group pruning with parquet min/max statistics.

Parity: the reference delegates page/row-group filtering to DataFusion's
parquet source gated by `auron.parquet.enable.pageFiltering` (ref
conf.rs:43, parquet_exec.rs).  Here: interval analysis of the filter
PhysicalExpr against per-row-group [min, max] statistics — a conservative
evaluator that returns "maybe" unless stats prove a group empty.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from blaze_tpu.exprs.base import BoundReference, Literal, PhysicalExpr
from blaze_tpu.exprs.binary import BinaryExpr
from blaze_tpu.exprs.conditional import InList, IsNotNull, IsNull
from blaze_tpu.schema import Schema

Interval = Tuple[Optional[object], Optional[object], bool]  # (min, max, has_nulls)


def prune_with_stats(md, schema: Schema, predicate: PhysicalExpr,
                     groups: List[int]) -> List[int]:
    name_to_col = {md.schema.column(i).name: i
                   for i in range(len(md.schema))}
    keep = []
    for g in groups:
        rg = md.row_group(g)
        stats = {}
        for name, ci in name_to_col.items():
            col = rg.column(ci)
            if col.statistics is not None and col.statistics.has_min_max:
                stats[name] = (col.statistics.min, col.statistics.max,
                               (col.statistics.null_count or 0) > 0)
        if _may_match(predicate, schema, stats):
            keep.append(g)
    return keep


def _col_name(expr: PhysicalExpr, schema: Schema) -> Optional[str]:
    if isinstance(expr, BoundReference):
        if expr.name:
            return expr.name
        if expr.index < len(schema):
            return schema[expr.index].name
    return None


def _lit_value(expr: PhysicalExpr):
    if isinstance(expr, Literal):
        return expr.value
    return None


def _may_match(pred: PhysicalExpr, schema: Schema, stats: dict) -> bool:
    """Conservative: False only when stats PROVE no row matches."""
    if isinstance(pred, BinaryExpr):
        if pred.op == "and":
            return (_may_match(pred.left, schema, stats) and
                    _may_match(pred.right, schema, stats))
        if pred.op == "or":
            return (_may_match(pred.left, schema, stats) or
                    _may_match(pred.right, schema, stats))
        if pred.op in ("==", "<", "<=", ">", ">="):
            # normalize to col OP lit
            name, lit, op = _col_name(pred.left, schema), _lit_value(pred.right), pred.op
            if name is None and _col_name(pred.right, schema) is not None:
                flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "==": "=="}
                name, lit, op = (_col_name(pred.right, schema),
                                 _lit_value(pred.left), flip[pred.op])
            if name is None or lit is None or name not in stats:
                return True
            mn, mx, _ = stats[name]
            try:
                if op == "==":
                    return mn <= lit <= mx
                if op == "<":
                    return mn < lit
                if op == "<=":
                    return mn <= lit
                if op == ">":
                    return mx > lit
                if op == ">=":
                    return mx >= lit
            except TypeError:
                return True
        return True
    if isinstance(pred, InList) and not pred.negated:
        name = _col_name(pred.child, schema)
        if name is None or name not in stats:
            return True
        mn, mx, _ = stats[name]
        try:
            return any(v is not None and mn <= v <= mx for v in pred.values)
        except TypeError:
            return True
    if isinstance(pred, IsNull):
        name = _col_name(pred.child, schema)
        if name is not None and name in stats:
            return stats[name][2]
        return True
    return True
