"""Table sinks: native Parquet write.

Parity: parquet_sink_exec.rs:532 (native write of Hive-insert data through
host output streams; NativeParquetSinkUtils) — here pyarrow's C++ parquet
writer plays the native-writer role.  Hive-style partitioned layout when
partition_cols given.  ORC output is gated on pyarrow's ORC writer
(orc_sink_exec.rs:568 parity).
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

import pyarrow as pa
import pyarrow.parquet as pq

from blaze_tpu.batch import ColumnBatch
from blaze_tpu.ops.base import BatchIterator, ExecutionPlan
from blaze_tpu.schema import Schema


def write_parquet_atomic(table: pa.Table, path: str,
                         compression: str = "zstd") -> int:
    """Crash-safe single-file write: full file lands under a dot-tmp
    name, then renames into place — a reader (or a streaming recovery
    scan) never sees a torn parquet footer.  Returns bytes written."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = os.path.join(os.path.dirname(path),
                       f".{os.path.basename(path)}.tmp-{os.getpid()}")
    pq.write_table(table, tmp, compression=compression)
    nbytes = os.path.getsize(tmp)
    os.replace(tmp, path)
    return nbytes


class ParquetSinkExec(ExecutionPlan):

    def __init__(self, child: ExecutionPlan, path: str,
                 partition_cols: Optional[Sequence[str]] = None,
                 compression: str = "zstd"):
        super().__init__([child])
        self.path = path
        self.partition_cols = list(partition_cols or [])
        self.compression = compression

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self, partition: int) -> BatchIterator:
        child = self.children[0]
        batches = [b.compact().to_arrow() for b in child.execute(partition)]
        batches = [b for b in batches if b.num_rows]
        if not batches:
            return iter(())
        table = pa.Table.from_batches(batches)
        rows = table.num_rows
        if self.partition_cols:
            pq.write_to_dataset(table, self.path,
                                partition_cols=self.partition_cols,
                                compression=self.compression,
                                basename_template=(
                                    f"part-{partition}-{{i}}.parquet"))
        else:
            os.makedirs(self.path, exist_ok=True)
            out = os.path.join(self.path, f"part-{partition:05d}.parquet")
            pq.write_table(table, out, compression=self.compression)
        self.metrics.add("output_rows", rows)
        self.metrics.add("io_bytes", table.nbytes)
        return iter(())


class OrcSinkExec(ExecutionPlan):
    """(ref orc_sink_exec.rs:568)"""

    def __init__(self, child: ExecutionPlan, path: str):
        super().__init__([child])
        self.path = path

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self, partition: int) -> BatchIterator:
        from pyarrow import orc
        child = self.children[0]
        batches = [b.compact().to_arrow() for b in child.execute(partition)]
        batches = [b for b in batches if b.num_rows]
        if not batches:
            return iter(())
        table = pa.Table.from_batches(batches)
        os.makedirs(self.path, exist_ok=True)
        out = os.path.join(self.path, f"part-{partition:05d}.orc")
        orc.write_table(table, out)
        self.metrics.add("output_rows", table.num_rows)
        self.metrics.add("io_bytes", table.nbytes)
        return iter(())
