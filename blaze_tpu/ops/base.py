"""Execution operator base + the batch-coalescing stream.

Parity: DataFusion `ExecutionPlan` as used by the reference's 28 operators
(ref: datafusion-ext-plans/src/*, planner.rs:122 create_plan) and the
CoalesceStream auto-wrapped around every plan root
(ref: common/execution_context.rs:146-150, rt.rs:160-166).

Execution model (TPU-first): synchronous pull iterators of ColumnBatch per
partition.  The reference's tokio async streams exist to overlap JVM IO with
native compute; here overlap comes from (a) the host prefetch thread in the
task runtime (bridge/runtime.py) and (b) XLA async dispatch — device work is
enqueued ahead while the host iterates.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

from blaze_tpu import config
from blaze_tpu.batch import ColumnBatch, round_capacity
from blaze_tpu.bridge.context import current_task
from blaze_tpu.bridge.metrics import MetricNode
from blaze_tpu.schema import Schema

BatchIterator = Iterator[ColumnBatch]


class ExecutionPlan:
    """One physical operator node."""

    def __init__(self, children: Sequence["ExecutionPlan"] = ()):
        self._children: List[ExecutionPlan] = list(children)
        self.metrics = MetricNode(name=type(self).__name__)

    # -- topology -----------------------------------------------------------
    @property
    def children(self) -> List["ExecutionPlan"]:
        return self._children

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    @property
    def num_partitions(self) -> int:
        """Output partition count (Spark RDD partitions analog)."""
        if self._children:
            return self._children[0].num_partitions
        return 1

    # -- execution ----------------------------------------------------------
    def execute(self, partition: int) -> BatchIterator:
        """Pull-stream of batches for one partition."""
        raise NotImplementedError

    def arrow_batches(self, partition: int):
        """Pull-stream of Arrow record batches.  Host-resident consumers
        (Acero joins, host-vectorized agg) use this to stay
        Arrow-resident; sources that already hold Arrow data override it
        to skip the ColumnBatch round trip entirely."""
        for cb in self.execute(partition):
            cb = cb.compact()
            if cb.num_rows:
                yield cb.to_arrow()

    def execute_collect(self) -> "ColumnBatch":
        """All partitions concatenated (test/driver helper)."""
        out = []
        for p in range(self.num_partitions):
            out.extend(self.execute(p))
        if not out:
            from blaze_tpu.batch import ColumnBatch as CB
            import pyarrow as pa
            empty = pa.Table.from_batches([], schema=self.schema.to_arrow())
            return CB.from_arrow(empty)
        return ColumnBatch.concat(out)

    def collect_metrics(self) -> MetricNode:
        node = MetricNode(name=type(self).__name__, values=dict(self.metrics.values))
        node.children = [c.collect_metrics() for c in self._children]
        return node

    def __repr__(self):
        head = type(self).__name__
        if not self._children:
            return head
        inner = ", ".join(repr(c) for c in self._children)
        return f"{head}({inner})"

    def pretty(self, indent: int = 0) -> str:
        lines = ["  " * indent + type(self).__name__]
        for c in self._children:
            lines.append(c.pretty(indent + 1))
        return "\n".join(lines)


class CoalesceStream:
    """Re-batches a stream to ~batch_size dense rows.

    The reference coalesces small batches at every plan root and between
    operators (ref execution_context.rs:146 CoalesceStream).  Here it also
    compacts sparse selections: a batch whose surviving-row density is below
    `min_density` is compacted so downstream device work stops paying for
    dead lanes — the static-shape analog of selection vectors.
    """

    def __init__(self, stream: BatchIterator, batch_size: Optional[int] = None,
                 min_density: float = 0.5, metrics: Optional[MetricNode] = None):
        self._stream = stream
        self._batch_size = batch_size or config.BATCH_SIZE.get()
        self._min_density = min_density
        self._metrics = metrics or MetricNode()

    def __iter__(self) -> BatchIterator:
        staged: List[ColumnBatch] = []
        staged_rows = 0
        ctx = current_task()
        for batch in self._stream:
            ctx.check_running()
            n = batch.selected_count()
            if n == 0:
                continue
            density = n / max(1, batch.capacity)
            if density < self._min_density:
                batch = batch.compact()
            if n >= self._batch_size // 2 and not staged:
                yield batch
                continue
            staged.append(batch)
            staged_rows += n
            if staged_rows >= self._batch_size:
                yield ColumnBatch.concat(staged,
                                         round_capacity(staged_rows))
                staged, staged_rows = [], 0
        if staged:
            yield ColumnBatch.concat(staged, round_capacity(staged_rows))


def coalesce(stream: BatchIterator, batch_size: Optional[int] = None) -> BatchIterator:
    return iter(CoalesceStream(stream, batch_size))
