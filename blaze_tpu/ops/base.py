"""Execution operator base + the batch-coalescing stream.

Parity: DataFusion `ExecutionPlan` as used by the reference's 28 operators
(ref: datafusion-ext-plans/src/*, planner.rs:122 create_plan) and the
CoalesceStream auto-wrapped around every plan root
(ref: common/execution_context.rs:146-150, rt.rs:160-166).

Execution model (TPU-first): synchronous pull iterators of ColumnBatch per
partition.  The reference's tokio async streams exist to overlap JVM IO with
native compute; here overlap comes from (a) the host prefetch thread in the
task runtime (bridge/runtime.py) and (b) XLA async dispatch — device work is
enqueued ahead while the host iterates.
"""

from __future__ import annotations

import functools
import queue
import threading
import time
from typing import Callable, Iterator, List, Optional, Sequence

from blaze_tpu import config
from blaze_tpu.batch import ColumnBatch, bucket_capacity
from blaze_tpu.bridge.context import current_task
from blaze_tpu.bridge.metrics import BASELINE_METRICS, MetricNode
from blaze_tpu.schema import Schema

BatchIterator = Iterator[ColumnBatch]

# Per-thread set of operator-instance ids currently inside a metered
# stream.  Several operators route execute() through their own
# arrow_batches() (or vice versa); the guard makes the inner self-call
# pass through unmetered so rows/time are not double-counted.
_metering = threading.local()


def _active_ids() -> set:
    ids = getattr(_metering, "ids", None)
    if ids is None:
        ids = _metering.ids = set()
    return ids


def _batch_rows(item) -> int:
    sc = getattr(item, "selected_count", None)  # ColumnBatch
    if sc is not None:
        return sc()
    return getattr(item, "num_rows", 0)  # pyarrow RecordBatch


class _MeteredIter:
    """Wraps an operator's batch stream: per-next() wall time goes to
    `elapsed_compute_ns` (INCLUSIVE of child pull; renderers derive
    self-time), rows/batches counted per yield.  Metrics accumulate
    incrementally so a downstream early break (LimitExec) still records
    the partial work."""

    __slots__ = ("_it", "_plan", "_key", "_partition", "_kind",
                 "_total_ns", "_done")

    def __init__(self, it, plan, key, partition, kind, setup_ns):
        self._it = iter(it)
        self._plan = plan
        self._key = key
        self._partition = partition
        self._kind = kind
        self._total_ns = setup_ns
        self._done = False

    def __iter__(self):
        return self

    def __next__(self):
        active = _active_ids()
        reenter = self._key in active
        if not reenter:
            # cooperative cancellation at every metered batch step: a
            # cancelled/overdue query stops within one batch no matter
            # which operator is driving (reentrant self-calls skip the
            # check — the outer frame already ran it this step)
            current_task().check_running()
            active.add(self._key)
        t0 = time.perf_counter_ns()
        try:
            item = next(self._it)
        except StopIteration:
            self._finish()
            raise
        finally:
            dt = time.perf_counter_ns() - t0
            self._plan.metrics.add("elapsed_compute_ns", dt)
            self._total_ns += dt
            if not reenter:
                active.discard(self._key)
        m = self._plan.metrics
        m.add("output_batches")
        m.add("output_rows", _batch_rows(item))
        return item

    def _finish(self):
        if self._done:
            return
        self._done = True
        from blaze_tpu.bridge import tracing
        if tracing.enabled():
            tracing.emit_span(
                f"operator:{type(self._plan).__name__}",
                self._total_ns, partition=self._partition,
                kind=self._kind,
                rows=self._plan.metrics.get("output_rows"))


def _meter_stream(fn, kind: str):
    """Wrap a subclass execute/arrow_batches with the standard meter."""

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        active = _active_ids()
        key = id(self)
        if key in active:  # inner self-call (execute <-> arrow_batches)
            return fn(self, *args, **kwargs)
        partition = args[0] if args else kwargs.get("partition", 0)
        active.add(key)
        t0 = time.perf_counter_ns()
        try:
            # eager call under the meter: operators like IpcWriterExec do
            # all their work here and return an empty iterator
            it = fn(self, *args, **kwargs)
        finally:
            setup_ns = time.perf_counter_ns() - t0
            active.discard(key)
        self.metrics.add("elapsed_compute_ns", setup_ns)
        return _MeteredIter(it, self, key, partition, kind, setup_ns)

    wrapper._blaze_metered = True
    wrapper._blaze_wraps = fn
    return wrapper


class _Raised:
    """Worker-side exception in transit to the consumer."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


_DONE = object()


class PrefetchIterator:
    """Bounded-depth background prefetch of a batch stream — the async
    pipelined executor applied at host-IO edges (parquet row-group decode,
    shuffle IPC segment reads, map-side materialization).  The reference
    gets IO/compute overlap from tokio streams + sync_channel (rt.rs:142);
    here a single worker thread pulls `source` (optionally applying
    `transform`, e.g. Arrow decode + device placement, so that work also
    leaves the consumer's critical path) into a bounded queue.

    Contract:
      * ordering preserved (one worker, FIFO queue);
      * a source/transform exception is re-raised at the consumer, in
        position, after every item produced before it;
      * close() stops AND joins the worker — no leaked threads; called on
        early downstream termination and from __del__;
      * depth <= 0, or the `auron.tpu.io.prefetch` kill-switch off,
        degrades to a fully synchronous passthrough (no thread).
    """

    def __init__(self, source, depth: Optional[int] = None,
                 transform: Optional[Callable] = None,
                 name: str = "prefetch"):
        if depth is None:
            depth = (config.IO_PREFETCH_DEPTH.get()
                     if config.IO_PREFETCH_ENABLE.get() else 0)
        self._source = iter(source)
        self._transform = transform
        self._done = False
        if depth <= 0:
            self._queue = None
            self._thread = None
            return
        # the worker re-enters the consumer's TaskContext: cancellation
        # checks and task-scoped state are thread-local
        self._ctx = current_task()
        self._queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._work, name=f"blaze-prefetch-{name}", daemon=True)
        self._thread.start()

    # -- worker --------------------------------------------------------------
    def _work(self):
        from blaze_tpu.bridge.context import task_scope
        try:
            with task_scope(self._ctx):
                for item in self._source:
                    if self._transform is not None:
                        item = self._transform(item)
                    if not self._put(item):
                        return  # closed under us
            self._put(_DONE)
        except BaseException as exc:
            self._put(_Raised(exc))
        finally:
            close = getattr(self._source, "close", None)
            if close is not None:
                try:
                    close()
                except BaseException:
                    pass

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    # -- consumer ------------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._queue is None:  # synchronous passthrough
            item = next(self._source)
            return (self._transform(item) if self._transform is not None
                    else item)
        if self._done:
            raise StopIteration
        from blaze_tpu.bridge import xla_stats
        t0 = time.perf_counter_ns()
        item = self._queue.get()
        xla_stats.note_prefetch(wait_ns=time.perf_counter_ns() - t0)
        if item is _DONE:
            self._done = True
            self._thread.join(timeout=10)
            raise StopIteration
        if isinstance(item, _Raised):
            self._done = True
            self._thread.join(timeout=10)
            raise item.exc
        xla_stats.note_prefetch(batches=1)
        return item

    def close(self):
        """Stop + join the worker, draining the queue so a blocked put
        unblocks.  Idempotent; safe after exhaustion."""
        if self._queue is None or self._done:
            self._done = True
            return
        self._done = True
        self._stop.set()
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=10)

    def __del__(self):
        try:
            self.close()
        except BaseException:
            pass


def prefetch(source, depth: Optional[int] = None,
             transform: Optional[Callable] = None,
             name: str = "prefetch"):
    """Wrap a host-IO stream with the bounded background prefetcher (see
    PrefetchIterator); semantics of the stream are unchanged."""
    return PrefetchIterator(source, depth=depth, transform=transform,
                            name=name)


class ExecutionPlan:
    """One physical operator node.

    Every subclass's `execute`/`arrow_batches` override is wrapped at
    class-creation time with the standard meter, so all operators emit
    the BASELINE_METRICS vocabulary (output_rows, output_batches,
    elapsed_compute_ns, spilled_bytes, mem_used, io_bytes) without
    per-operator bookkeeping; operator code only adds extras
    (pruned_row_groups, spill_count, ...).
    """

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        for attr in ("execute", "arrow_batches"):
            fn = cls.__dict__.get(attr)
            if fn is not None and callable(fn) and \
                    not getattr(fn, "_blaze_metered", False):
                setattr(cls, attr, _meter_stream(fn, attr))

    def __init__(self, children: Sequence["ExecutionPlan"] = ()):
        self._children: List[ExecutionPlan] = list(children)
        self.metrics = MetricNode(name=type(self).__name__)
        for m in BASELINE_METRICS:
            self.metrics.values.setdefault(m, 0)

    # -- topology -----------------------------------------------------------
    @property
    def children(self) -> List["ExecutionPlan"]:
        return self._children

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    @property
    def num_partitions(self) -> int:
        """Output partition count (Spark RDD partitions analog)."""
        if self._children:
            return self._children[0].num_partitions
        return 1

    @property
    def reexecutable(self) -> bool:
        """Whether execute(partition) can be called again from scratch
        (file/memory-backed sources: yes).  The device-resident stage
        loop (plan/stage_compiler.py) only admits stages whose source
        is re-executable, because its wholesale fallback re-runs the
        partition through the staged path.  One-shot streams (already-
        consumed resource readers) must override this to False."""
        if self._children:
            return all(c.reexecutable for c in self._children)
        return True

    # -- execution ----------------------------------------------------------
    def execute(self, partition: int) -> BatchIterator:
        """Pull-stream of batches for one partition."""
        raise NotImplementedError

    def arrow_batches(self, partition: int):
        """Pull-stream of Arrow record batches.  Host-resident consumers
        (Acero joins, host-vectorized agg) use this to stay
        Arrow-resident; sources that already hold Arrow data override it
        to skip the ColumnBatch round trip entirely."""
        for cb in self.execute(partition):
            cb = cb.compact()
            if cb.num_rows:
                yield cb.to_arrow()

    def execute_collect(self) -> "ColumnBatch":
        """All partitions concatenated (test/driver helper)."""
        out = []
        for p in range(self.num_partitions):
            out.extend(self.execute(p))
        if not out:
            from blaze_tpu.batch import ColumnBatch as CB
            import pyarrow as pa
            empty = pa.Table.from_batches([], schema=self.schema.to_arrow())
            return CB.from_arrow(empty)
        return ColumnBatch.concat(out)

    def collect_metrics(self) -> MetricNode:
        node = MetricNode(name=type(self).__name__, values=dict(self.metrics.values))
        node.children = [c.collect_metrics() for c in self._children]
        return node

    def __repr__(self):
        head = type(self).__name__
        if not self._children:
            return head
        inner = ", ".join(repr(c) for c in self._children)
        return f"{head}({inner})"

    def pretty(self, indent: int = 0) -> str:
        lines = ["  " * indent + type(self).__name__]
        for c in self._children:
            lines.append(c.pretty(indent + 1))
        return "\n".join(lines)


def effective_batch_size(base: Optional[int] = None) -> int:
    """Coalesce target honouring the active query's degradation ladder:
    each `shrink-capacity` rung halves the target (floor 256 rows), so a
    quota-breaching query re-batches smaller and retains less state."""
    from blaze_tpu.bridge.context import active_query
    size = base or config.BATCH_SIZE.get()
    q = active_query()
    if q is not None:
        shrink = getattr(q, "capacity_shrink", 0)
        if shrink:
            size = max(256, size >> shrink)
    return size


class CoalesceStream:
    """Re-batches a stream to ~batch_size dense rows.

    The reference coalesces small batches at every plan root and between
    operators (ref execution_context.rs:146 CoalesceStream).  Here it also
    compacts sparse selections: a batch whose surviving-row density is below
    `min_density` is compacted so downstream device work stops paying for
    dead lanes — the static-shape analog of selection vectors.
    """

    def __init__(self, stream: BatchIterator, batch_size: Optional[int] = None,
                 min_density: float = 0.5, metrics: Optional[MetricNode] = None):
        self._stream = stream
        self._batch_size = batch_size or config.BATCH_SIZE.get()
        self._min_density = min_density
        self._metrics = metrics or MetricNode()

    def __iter__(self) -> BatchIterator:
        staged: List[ColumnBatch] = []
        staged_rows = 0
        ctx = current_task()
        for batch in self._stream:
            ctx.check_running()
            # re-evaluated per batch so a mid-query degradation rung
            # takes effect at the next boundary
            target = effective_batch_size(self._batch_size)
            n = batch.selected_count()
            if n == 0:
                continue
            density = n / max(1, batch.capacity)
            if density < self._min_density:
                batch = batch.compact()
            if n >= target // 2 and not staged:
                yield batch
                continue
            staged.append(batch)
            staged_rows += n
            if staged_rows >= target:
                yield ColumnBatch.concat(staged,
                                         bucket_capacity(staged_rows))
                staged, staged_rows = [], 0
        if staged:
            yield ColumnBatch.concat(staged, bucket_capacity(staged_rows))


def coalesce(stream: BatchIterator, batch_size: Optional[int] = None) -> BatchIterator:
    return iter(CoalesceStream(stream, batch_size))
