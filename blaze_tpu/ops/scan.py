"""Scan operators: in-memory (tests) and Parquet.

Parity: parquet_exec.rs:70 (DataFusion parquet source through the JVM Hadoop
FS bridge, page filtering + bloom gated by conf) and the TestMemoryExec
pattern used across the reference's Rust unit tests (SURVEY.md §4 tier 1).

TPU-first: parquet decoding is host work (pyarrow's C++ reader), producing
Arrow batches that cross to device as padded columns.  Predicate pushdown =
row-group min/max pruning + pyarrow filter pushdown; the residual predicate
still runs on device in FilterExec (scans never trust pushdown completeness,
matching the reference).
"""

from __future__ import annotations

import operator
from typing import Iterator, List, Optional, Sequence

import pyarrow as pa
import pyarrow.dataset
import pyarrow.parquet as pq

from blaze_tpu import config
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.ops.base import BatchIterator, ExecutionPlan
from blaze_tpu.schema import Schema

def open_source(path: str):
    """Local paths pass through; scheme'd paths (hdfs://, s3://...) open
    through the registered FsProvider — the host-engine FS callback path
    (ref hadoop_fs.rs InternalFileReader)."""
    if "://" in path and not path.startswith("file://"):
        from blaze_tpu.bridge.fs import fs_provider
        return fs_provider.provide(path).open(path)
    return path


class _MetaLru:
    """Bounded LRU for parquet footer metadata, keyed by path with the
    file mtime as validity stamp: a rewritten file refreshes IN PLACE (no
    stale twin lingering under an old (path, mtime) key), touches move
    entries to the MRU end, and inserts evict from the LRU end — a
    long-running session holds at most `metadataCacheSize` footers."""

    def __init__(self):
        import collections
        import threading
        self._lock = threading.Lock()
        self._entries = collections.OrderedDict()  # path -> (mtime, md)

    def get(self, path: str, mtime: float):
        with self._lock:
            entry = self._entries.get(path)
            if entry is None or entry[0] != mtime:
                if entry is not None:
                    del self._entries[path]  # stale: mtime moved
                return None
            self._entries.move_to_end(path)
            return entry[1]

    def put(self, path: str, mtime: float, md) -> None:
        limit = max(1, config.PARQUET_METADATA_CACHE_SIZE.get())
        with self._lock:
            self._entries[path] = (mtime, md)
            self._entries.move_to_end(path)
            while len(self._entries) > limit:
                self._entries.popitem(last=False)

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def clear(self):
        with self._lock:
            self._entries.clear()


_META_CACHE = _MetaLru()


def parquet_metadata(path: str):
    """Footer metadata cached across scans and fused-stage bound discovery
    (ref auron.parquet.metadataCacheSize; validated by mtime so rewritten
    files refresh).  Remote paths have no local mtime to invalidate on, so
    they bypass the cache rather than serve stale footers after an
    in-place rewrite."""
    import os
    if "://" in path and not path.startswith("file://"):
        return pq.ParquetFile(open_source(path)).metadata
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        mtime = 0
    md = _META_CACHE.get(path, mtime)
    if md is None:
        md = pq.ParquetFile(open_source(path)).metadata
        _META_CACHE.put(path, mtime, md)
    return md


class MemoryScanExec(ExecutionPlan):
    """Fixed batches per partition (the TestMemoryExec analog)."""

    def __init__(self, schema: Schema,
                 partitions: Sequence[Sequence[ColumnBatch]]):
        super().__init__()
        self._schema = schema
        self._partitions = [list(p) for p in partitions]

    @staticmethod
    def from_arrow(table, num_partitions: int = 1,
                   batch_rows: Optional[int] = None) -> "MemoryScanExec":
        if isinstance(table, pa.RecordBatch):
            table = pa.Table.from_batches([table])
        if config.ENCODING_DICT_ENABLE.get():
            table = _dict_encode_table(table)
        schema = Schema.from_arrow(table.schema)
        batch_rows = batch_rows or config.BATCH_SIZE.get()
        batches = table.to_batches(max_chunksize=batch_rows)
        parts: List[List[ColumnBatch]] = [[] for _ in range(num_partitions)]
        for i, rb in enumerate(batches):
            parts[i % num_partitions].append(ColumnBatch.from_arrow(rb))
        return MemoryScanExec(schema, parts)

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def num_partitions(self) -> int:
        return len(self._partitions)

    def execute(self, partition: int) -> BatchIterator:
        for b in self._partitions[partition]:
            yield b


class ParquetScanExec(ExecutionPlan):
    """Parquet scan over a list of file splits.

    Each partition owns a list of (path, row_group_range) splits, mirroring
    the FileScanConfig file groups of parquet_exec.rs:70.  `predicate` is a
    PhysicalExpr evaluated twice: statically against row-group min/max stats
    here (pruning, ref conf auron.parquet.enable.pageFiltering), and
    row-wise on device by the FilterExec above this scan.
    """

    def __init__(self, schema: Schema, file_groups: Sequence[Sequence[str]],
                 projection: Optional[Sequence[str]] = None,
                 predicate=None, batch_rows: Optional[int] = None,
                 partition_schema: Optional[Schema] = None,
                 partition_values: Optional[Sequence[Sequence[Sequence]]]
                 = None):
        super().__init__()
        self._file_schema = schema
        # Hive-style partition-constant columns: the reference's
        # relation.schema is file columns + partition columns, and the
        # projection selects from that COMBINED space in projection order
        # (ref FileScanExecConf, NativeParquetScanBase.scala:55,
        # planner.rs:170-200).  A projected plan emits exactly the
        # projected columns; an unprojected one emits file cols + all
        # partition cols.
        self._partition_schema = partition_schema
        self._partition_values = partition_values  # [group][file][field]
        part_names = ({f.name for f in partition_schema}
                      if partition_schema is not None else set())
        self._projection = list(projection) if projection is not None else None
        if self._projection is not None:
            file_part = Schema([schema.field(n) for n in self._projection
                                if n not in part_names])
            self._out_partition_fields = [
                partition_schema.field(n) for n in self._projection
                if n in part_names] if partition_schema is not None else []
            combined = {f.name: f for f in schema}
            if partition_schema is not None:
                combined.update({f.name: f for f in partition_schema})
            self._schema = Schema([combined[n] for n in self._projection])
        else:
            file_part = schema
            self._out_partition_fields = (list(partition_schema)
                                          if partition_schema is not None
                                          else [])
            self._schema = (Schema(list(schema) + list(partition_schema))
                            if partition_schema is not None else schema)
        self._file_part = file_part
        self._file_groups = [list(g) for g in file_groups]
        self._predicate = predicate
        self._batch_rows = batch_rows or config.BATCH_SIZE.get()

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def num_partitions(self) -> int:
        return len(self._file_groups)

    def execute(self, partition: int) -> BatchIterator:
        # decode AND ColumnBatch conversion (incl. device placement) run on
        # the prefetch worker: the next batch's pyarrow decode + H2D
        # overlap downstream compute (double-buffering; kill-switch
        # auron.tpu.io.prefetch)
        from blaze_tpu.ops.base import prefetch
        transform = ColumnBatch.from_arrow
        post = self._post_decode_filter()
        # per-stream incremental dictionary encoder: each execute() call
        # owns one (the running dictionary is stream state — codes are
        # only comparable within a stream, and each batch's dictionary
        # extends the previous batch's, so the LAST dictionary seen
        # decodes every earlier batch of the stream)
        enc = _stream_dict_encoder(self._schema)
        if post is not None or enc is not None:
            def transform(rb, _post=post, _enc=enc):
                if _enc is not None:
                    rb = _enc(rb)
                cb = ColumnBatch.from_arrow(rb)
                return _post(cb) if _post is not None else cb
        return prefetch(self._decode_batches(partition),
                        depth=self._prefetch_depth(),
                        transform=transform,
                        name="parquet_scan")

    @staticmethod
    def _prefetch_depth():
        """Default double-buffering depth, widened to one stage-loop
        chunk when the device-resident loop is active: the loop consumes
        a whole chunk of batches per dispatch, so a depth-2 ring would
        stall it on decode every chunk."""
        from blaze_tpu import config
        if not config.IO_PREFETCH_ENABLE.get():
            return 0
        depth = config.IO_PREFETCH_DEPTH.get()
        from blaze_tpu.plan.stage_compiler import stage_loop_active
        if stage_loop_active():
            depth = max(depth, config.STAGE_DEVICE_LOOP_CHUNK.get())
        return depth

    def _post_decode_filter(self):
        """Scan-embedded filtering: when the pushdown predicate is fully
        traceable, the fused filter program ANDs its exact row mask into
        each decoded batch ON THE PREFETCH WORKER — the mask computation
        overlaps downstream compute, and the Filter operator above (which
        evaluates the same conjuncts) re-ANDs an identical mask.  Only
        applies when the output schema is the file schema (the predicate
        is bound against file-column ordinals; projections / partition
        columns reorder the space)."""
        if self._predicate is None or self._projection is not None \
                or self._partition_schema is not None:
            return None
        from blaze_tpu.exprs.program import fused_filter
        return fused_filter([self._predicate], self._schema)

    def arrow_batches(self, partition: int, extra_prune=None):
        """Prefetched Arrow-resident scan stream (see _decode_batches)."""
        from blaze_tpu.ops.base import prefetch
        return prefetch(self._decode_batches(partition, extra_prune),
                        name="parquet_scan")

    def _decode_batches(self, partition: int, extra_prune=None):
        """Arrow-resident scan stream.  Files under the eager threshold
        decode with pq.read_row_groups (multithreaded column decode,
        measurably faster than the single-threaded iter_batches slicer);
        batches re-slice zero-copy to the engine batch size.  Larger
        files stream through iter_batches for bounded memory.

        `extra_prune`: a pruning-ONLY predicate scoped to THIS read —
        joins pass the build-side join-key [min, max] runtime filter here
        so row groups provably outside the build range never decode (the
        reference pushes its bloom runtime filters into the probe scan
        the same way, ref bloom_filter_might_contain.rs + parquet page
        filtering).  It prunes via statistics only; exact row filtering
        stays with the caller.  Passing it per-read keeps the shared
        plan node immutable across partitions/executions."""
        import os
        prune_pred = self._predicate
        if extra_prune is not None:
            from blaze_tpu.exprs.binary import BinaryExpr
            prune_pred = (extra_prune if prune_pred is None
                          else BinaryExpr("and", prune_pred, extra_prune))
        eager_limit = config.SCAN_EAGER_FILE_BYTES.get()
        group = self._file_groups[partition]
        columns = ([f.name for f in self._file_part]
                   if self._projection is not None else None)
        # whole-group fast path: one multithreaded read across all files
        # (parallelism spans files, not just row groups within one)
        if (len(group) > 1 and prune_pred is None
                and not self._out_partition_fields
                and all(isinstance(p, str) and os.path.exists(p)
                        for p in group)
                and sum(os.path.getsize(p) for p in group) <= eager_limit):
            try:
                tbl = pq.read_table(group, columns=columns,
                                    use_threads=True)
            except Exception:
                pass  # schema evolution across files: per-file loop
            else:
                for rb in tbl.to_batches(max_chunksize=self._batch_rows):
                    if rb.num_rows == 0:
                        continue
                    rb = _align_schema(rb, self._file_part)
                    self.metrics.add("io_bytes", rb.nbytes)
                    yield rb
                return
        share_max = (config.CACHE_SCAN_SHARE_MAX_BYTES.get()
                     if config.CACHE_ENABLE.get()
                     and config.CACHE_SCAN_SHARE.get() else 0)
        for fidx, path in enumerate(self._file_groups[partition]):
            try:
                f = pq.ParquetFile(open_source(path))
            except Exception:
                if config.IGNORE_CORRUPTED_FILES.get():
                    continue
                raise
            row_groups = self._prune_row_groups(f, prune_pred)
            self.metrics.add("pruned_row_groups",
                             f.metadata.num_row_groups - len(row_groups))
            if not row_groups:
                continue
            if (share_max and isinstance(path, str)
                    and os.path.exists(path)
                    and os.path.getsize(path) <= share_max):
                yield from self._share_file(f, path, row_groups, columns,
                                            partition, fidx)
                continue
            if (isinstance(path, str) and os.path.exists(path)
                    and os.path.getsize(path) <= eager_limit):
                tbl = f.read_row_groups(row_groups, columns=columns,
                                        use_threads=True)
                batches = tbl.to_batches(max_chunksize=self._batch_rows)
            else:
                batches = f.iter_batches(batch_size=self._batch_rows,
                                         row_groups=row_groups,
                                         columns=columns)
            for rb in batches:
                if rb.num_rows == 0:
                    continue
                rb = _align_schema(rb, self._file_part)
                self.metrics.add("io_bytes", rb.nbytes)
                yield self._assemble_output(rb, partition, fidx)

    def _share_file(self, f, path, row_groups, columns, partition, fidx):
        """Decode one file through the scan broker: concurrent scans of
        the same (file, row-groups, batch-rows) with a covered column
        set ride one decode pass.  The leader publishes RAW batches —
        alignment and partition-constant assembly stay per consumer, so
        a follower's output is bit-identical to its own decode."""
        from blaze_tpu.bridge import xla_stats
        from blaze_tpu.bridge.context import active_query
        from blaze_tpu.cache import scanshare
        broker = scanshare.get_broker()
        mode, entry = broker.lease(path, row_groups, columns,
                                   self._batch_rows)
        try:
            raw = None
            if mode == "follow":
                q = active_query()
                raw = scanshare.follow_batches(
                    entry, check=q.check if q is not None else None)
            if raw is None:
                # leader — or a follower decoding itself after the
                # leader failed (its error is the leader's to surface)
                tbl = f.read_row_groups(row_groups, columns=columns,
                                        use_threads=True)
                raw = tbl.to_batches(max_chunksize=self._batch_rows)
                if mode == "lead":
                    broker.publish(entry, list(raw))
                    raw = entry.batches
                    xla_stats.note_cache(scan_share_misses=1)
            for rb in raw:
                if rb.num_rows == 0:
                    continue
                rb = _align_schema(rb, self._file_part)
                self.metrics.add("io_bytes", rb.nbytes)
                yield self._assemble_output(rb, partition, fidx)
        except BaseException as e:  # noqa: BLE001 - unblock followers
            if mode == "lead" and not entry.event.is_set():
                broker.publish(entry, None, error=e)
            raise
        finally:
            broker.release(entry)

    def _assemble_output(self, rb: pa.RecordBatch, partition: int,
                         fidx: int) -> pa.RecordBatch:
        """Merge file columns with the projected partition constants into
        self._schema order (projection may interleave the two)."""
        if not self._out_partition_fields:
            return rb
        return assemble_partition_constants(
            rb, self._schema, self._partition_schema,
            self._partition_values, partition, fidx)

    def _prune_row_groups(self, f: pq.ParquetFile,
                          prune_pred=None) -> List[int]:
        md = f.metadata
        all_groups = list(range(md.num_row_groups))
        if (prune_pred is None or
                not config.PARQUET_ENABLE_PAGE_FILTERING.get()):
            return all_groups
        from blaze_tpu.ops.pruning import prune_with_stats
        return prune_with_stats(md, self._file_schema, prune_pred,
                                all_groups)


def assemble_partition_constants(rb: pa.RecordBatch, out_schema: Schema,
                                 partition_schema: Optional[Schema],
                                 partition_values, partition: int,
                                 fidx: int) -> pa.RecordBatch:
    """Merge file columns with Hive partition constants into
    `out_schema` order (FileScanConfig partition_values): missing or
    short per-file value lists null-fill.  ONE implementation for every
    scan format — the parquet and ORC scans must never drift on
    partition-constant semantics (r5 review)."""
    values: dict = {}
    if partition_values is not None and partition < len(partition_values):
        group = partition_values[partition]
        if fidx < len(group):
            values = {f.name: v for f, v in
                      zip(partition_schema, group[fidx])}
    by_name = {rb.schema.field(i).name: rb.column(i)
               for i in range(rb.num_columns)}
    arrays = []
    for fld in out_schema:
        if fld.name in by_name:
            arrays.append(by_name[fld.name])
            continue
        v = values.get(fld.name)
        at = fld.data_type.to_arrow()
        arrays.append(pa.nulls(rb.num_rows, type=at) if v is None
                      else pa.array([v] * rb.num_rows, type=at))
    return pa.RecordBatch.from_arrays(
        arrays, schema=out_schema.to_arrow())


def _stream_dict_encoder(schema: Schema):
    """A fresh per-stream encoder when dictionary encoding is on and the
    scan emits utf8 columns; None otherwise (the disabled path never
    touches the batch — byte-identical to pre-encoding behavior)."""
    from blaze_tpu.schema import TypeId
    if not config.ENCODING_DICT_ENABLE.get():
        return None
    if not any(f.data_type.id == TypeId.UTF8 for f in schema):
        return None
    return _StreamDictEncoder(schema, config.ENCODING_DICT_MAX_ENTRIES.get())


class _StreamDictEncoder:
    """Incremental per-stream dictionary encoding of utf8 scan columns.

    Each utf8 column keeps a running stream-global dictionary in
    first-seen order; every emitted batch's DictionaryArray indexes into
    the CURRENT global, so dictionaries grow by appending only (prefix
    property).  Downstream, a batch's codes therefore remain valid
    against any LATER dictionary of the same stream — the stage loop
    exploits this by decoding final group keys with the last dictionary
    snapshot it saw.

    Overflow past `auron.tpu.encoding.dict.maxEntries` retires the
    column for the rest of the stream: later batches carry plain utf8
    and downstream code (ColumnBatch.concat mixed branch, the stage-loop
    stream guard) degrades losslessly to host strings.
    """

    def __init__(self, schema: Schema, max_entries: int):
        from blaze_tpu.schema import TypeId
        # col index -> running dictionary (None = not started,
        # False = retired by overflow)
        self._cols = {i: None for i, f in enumerate(schema)
                      if f.data_type.id == TypeId.UTF8}
        self._noted: set = set()
        self._max = max(1, max_entries)

    def __call__(self, rb: pa.RecordBatch) -> pa.RecordBatch:
        import pyarrow.compute as pc
        arrays = list(rb.columns)
        changed = False
        for i, vals in list(self._cols.items()):
            if vals is False or i >= rb.num_columns:
                continue
            arr = rb.column(i)
            if pa.types.is_dictionary(arr.type):
                continue  # already encoded upstream
            if not pa.types.is_string(arr.type):
                arr = arr.cast(pa.string())
            if vals is None:
                vals = pa.array([], type=pa.string())
            pos = pc.index_in(arr, value_set=vals)
            missing = pc.and_(pc.is_valid(arr), pc.is_null(pos))
            if len(arr) and pc.any(missing).as_py():
                new_vals = pc.unique(arr.filter(missing)).cast(pa.string())
                if len(vals) + len(new_vals) > self._max:
                    # overflow: stop encoding this column for the stream
                    self._cols[i] = False
                    continue
                vals = pa.concat_arrays([vals, new_vals])
                pos = pc.index_in(arr, value_set=vals)
            self._cols[i] = vals
            if i not in self._noted:
                self._noted.add(i)
                from blaze_tpu.bridge import xla_stats
                xla_stats.note_encoding(dict_encoded_columns=1)
            arrays[i] = pa.DictionaryArray.from_arrays(
                pos.cast(pa.int32()), vals)
            changed = True
        if not changed:
            return rb
        return pa.RecordBatch.from_arrays(arrays, names=list(rb.schema.names))


def _dict_encode_table(table: pa.Table) -> pa.Table:
    """Whole-table dictionary encoding for memory scans: one unified
    dictionary per utf8 column (to_batches then slices it zero-copy, so
    every batch of the scan shares one dictionary — the concat fast
    path).  Columns whose cardinality exceeds maxEntries stay plain."""
    import pyarrow.compute as pc
    cap = max(1, config.ENCODING_DICT_MAX_ENTRIES.get())
    arrays, changed = [], False
    for i, f in enumerate(table.schema):
        col = table.column(i)
        if not pa.types.is_string(f.type):
            arrays.append(col)
            continue
        arr = (col.combine_chunks() if col.num_chunks != 1
               else col.chunk(0))
        if isinstance(arr, pa.ChunkedArray):
            arr = (arr.chunk(0) if arr.num_chunks
                   else pa.array([], type=pa.string()))
        enc = pc.dictionary_encode(arr)
        if len(enc.dictionary) > cap:
            arrays.append(col)
            continue
        from blaze_tpu.bridge import xla_stats
        xla_stats.note_encoding(dict_encoded_columns=1)
        arrays.append(enc)
        changed = True
    if not changed:
        return table
    return pa.Table.from_arrays(arrays, names=list(table.schema.names))


def _align_schema(rb: pa.RecordBatch, schema: Schema) -> pa.RecordBatch:
    """Cast physical file types to the plan's logical schema (schema
    evolution: missing columns -> nulls, widened ints, ts units)."""
    target = schema.to_arrow()
    if rb.schema.equals(target):
        return rb
    arrays = []
    for field in target:
        idx = rb.schema.get_field_index(field.name)
        if idx < 0:
            arrays.append(pa.nulls(rb.num_rows, type=field.type))
        else:
            col = rb.column(idx)
            arrays.append(col if col.type.equals(field.type)
                          else col.cast(field.type, safe=False))
    return pa.RecordBatch.from_arrays(arrays, schema=target)
