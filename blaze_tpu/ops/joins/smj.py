"""Streaming sort-merge join over key-sorted children.

Parity: sort_merge_join_exec.rs:397 + joins/smj/{full,semi,existence}_join.rs
and joins/stream_cursor.rs — both inputs arrive sorted ascending/nulls-first
on the join keys; the join walks equal-key RUNS with two cursors, emitting
the run cross-product (through the optional join filter) and never holding
more than the current runs in memory.

TPU-first shape: run boundaries are computed VECTORIZED per batch (adjacent
row equality via arrow kernels); only the run-level two-pointer walk is
sequential.  A run that touches a batch tail is carried until the key
changes, so runs may span batches without rescans.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from blaze_tpu import config
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.exprs import PhysicalExpr
from blaze_tpu.schema import Schema

class _Run:
    """One complete equal-key run: key tuple + the rows (arrow table)."""

    __slots__ = ("key", "table")

    def __init__(self, key: Tuple, table: pa.Table):
        self.key = key
        self.table = table

    @property
    def is_null_key(self) -> bool:
        # flag 0 = NULL (sorts first) never matches across sides.  NaN
        # (flag 2, sorts last) DOES match NaN: Spark treats NaN as a
        # normal value in join keys (NaN semantics doc; grouping and
        # joins both normalize NaN), so only nulls are excluded here.
        return any(k[0] == 0 for k in self.key)


def _key_tuple(arrays: List[pa.Array], row: int) -> Tuple:
    out = []
    for a in arrays:
        v = a[row]
        if not v.is_valid:
            out.append((0, 0))  # nulls first, never equal across sides
        else:
            py = v.as_py()
            if isinstance(py, float) and py != py:
                # NaN poisons tuple comparison (both < and > come back
                # False); encode it as a sorts-last flag with a fixed
                # payload so NaN == NaN, matching Spark join semantics.
                # (-0.0 needs no special case: tuple comparison already
                # treats -0.0 == 0.0.)
                out.append((2, 0))
            else:
                out.append((1, py))
    return tuple(out)


def _run_key_cmp(a: Tuple, b: Tuple) -> int:
    # null slots (flag 0) compare before values; null != null for matching
    # is handled by the caller via is_null_key
    if a < b:
        return -1
    if a > b:
        return 1
    return 0


class _RunCursor:
    """Pulls key-sorted batches and yields complete equal-key runs."""

    def __init__(self, batches: Iterator[pa.RecordBatch],
                 key_exprs: Sequence[PhysicalExpr], schema: Schema):
        self._batches = batches
        self._key_exprs = list(key_exprs)
        self._schema = schema
        self._pending: List[Tuple[Tuple, pa.Table]] = []  # complete runs
        self._tail: Optional[Tuple[Tuple, pa.Table]] = None
        self._done = False

    def _keys_of(self, rb: pa.RecordBatch) -> List[pa.Array]:
        cb = ColumnBatch.from_arrow(rb)
        out = []
        for e in self._key_exprs:
            out.append(e.evaluate(cb).to_host(rb.num_rows))
        return out

    def _ingest(self) -> None:
        """Pull one batch, split into runs; keep the last run as tail."""
        try:
            rb = next(self._batches)
        except StopIteration:
            if self._tail is not None:
                self._pending.append(self._tail)
                self._tail = None
            self._done = True
            return
        if rb.num_rows == 0:
            return
        keys = self._keys_of(rb)
        n = rb.num_rows
        # vectorized adjacent-equality -> run starts
        change = np.zeros(n, dtype=bool)
        change[0] = True
        for a in keys:
            cur = a.slice(1)
            prev = a.slice(0, n - 1)
            eq = pc.equal(cur, prev)
            both_null = pc.and_(pc.is_null(cur), pc.is_null(prev))
            same = pc.or_kleene(eq, both_null)
            if isinstance(same, pa.ChunkedArray):
                same = same.combine_chunks()
            same_np = np.asarray(same.fill_null(False))
            change[1:] |= ~same_np
        starts = np.nonzero(change)[0]
        ends = np.append(starts[1:], n)
        table = pa.Table.from_batches([rb])
        for s, e in zip(starts, ends):
            key = _key_tuple(keys, int(s))
            run_tbl = table.slice(int(s), int(e - s))
            if self._tail is not None:
                tkey, ttbl = self._tail
                if tkey == key:
                    self._tail = (tkey, pa.concat_tables([ttbl, run_tbl]))
                    continue
                self._pending.append(self._tail)
                self._tail = None
            self._tail = (key, run_tbl)

    def next_run(self) -> Optional[_Run]:
        while not self._pending and not self._done:
            self._ingest()
        if self._pending:
            key, tbl = self._pending.pop(0)
            return _Run(key, tbl)
        return None


class MergeJoiner:
    """Run-level merge of two sorted sides (the smj/*_join.rs dispatch)."""

    def __init__(self, left_schema: Schema, right_schema: Schema,
                 out_schema: Schema, join_type,
                 join_filter: Optional[PhysicalExpr],
                 existence_col: str = "exists"):
        from blaze_tpu.ops.joins.exec import JoinType
        self.JT = JoinType
        self.left_schema = left_schema
        self.right_schema = right_schema
        self.out_schema = out_schema
        self.join_type = join_type
        self.join_filter = join_filter
        self._batch_rows = config.BATCH_SIZE.get()

    # -- emission helpers ---------------------------------------------------
    def _null_side(self, schema: Schema, n: int) -> List[pa.Array]:
        return [pa.nulls(n, f.data_type.to_arrow()) for f in schema]

    def _emit_pairs(self, lt: pa.Table, rt: pa.Table,
                    l_idx: np.ndarray, r_idx: np.ndarray
                    ) -> Optional[pa.RecordBatch]:
        if not len(l_idx):
            return None
        lc = lt.take(pa.array(l_idx, type=pa.int64()))
        rc = rt.take(pa.array(r_idx, type=pa.int64()))
        arrays = [a.combine_chunks() for a in lc.columns] + \
                 [a.combine_chunks() for a in rc.columns]
        return pa.RecordBatch.from_arrays(
            arrays, schema=pa.schema(
                [f.to_arrow() for f in self.left_schema] +
                [f.to_arrow() for f in self.right_schema]))

    def _filter_pairs(self, lt: pa.Table, rt: pa.Table,
                      l_idx: np.ndarray, r_idx: np.ndarray) -> np.ndarray:
        """Boolean keep-mask over the candidate pairs."""
        if self.join_filter is None:
            return np.ones(len(l_idx), dtype=bool)
        rb = self._emit_pairs(lt, rt, l_idx, r_idx)
        if rb is None:
            return np.zeros(0, dtype=bool)
        cb = ColumnBatch.from_arrow(rb)
        v = self.join_filter.evaluate(cb)
        return np.asarray(v.as_mask(cb))[:rb.num_rows]

    def _project_out(self, rb: pa.RecordBatch) -> pa.RecordBatch:
        """Joined (left+right) rows -> output schema (inner/outer only)."""
        out_arrow = self.out_schema.to_arrow()
        arrays = [col.cast(f.type, safe=False)
                  if not col.type.equals(f.type) else col
                  for col, f in zip(rb.columns, out_arrow)]
        return pa.RecordBatch.from_arrays(arrays, schema=out_arrow)

    def _left_rows(self, tbl: pa.Table,
                   exists: Optional[bool] = None) -> pa.RecordBatch:
        arrays = [a.combine_chunks() for a in tbl.columns]
        if exists is not None:
            arrays = arrays + [pa.array([exists] * tbl.num_rows,
                                        type=pa.bool_())]
        return pa.RecordBatch.from_arrays(
            arrays, schema=self.out_schema.to_arrow())

    def _outer_left(self, tbl: pa.Table) -> pa.RecordBatch:
        arrays = [a.combine_chunks() for a in tbl.columns] + \
            self._null_side(self.right_schema, tbl.num_rows)
        return self._project_out(pa.RecordBatch.from_arrays(
            arrays, schema=pa.schema(
                [f.to_arrow() for f in self.left_schema] +
                [f.to_arrow() for f in self.right_schema])))

    def _outer_right(self, tbl: pa.Table) -> pa.RecordBatch:
        arrays = self._null_side(self.left_schema, tbl.num_rows) + \
            [a.combine_chunks() for a in tbl.columns]
        return self._project_out(pa.RecordBatch.from_arrays(
            arrays, schema=pa.schema(
                [f.to_arrow() for f in self.left_schema] +
                [f.to_arrow() for f in self.right_schema])))

    # -- the merge ----------------------------------------------------------
    def join(self, lcur: _RunCursor, rcur: _RunCursor
             ) -> Iterator[pa.RecordBatch]:
        JT = self.JT
        jt = self.join_type
        left_outer = jt in (JT.LEFT, JT.FULL)
        right_outer = jt in (JT.RIGHT, JT.FULL)
        lrun = lcur.next_run()
        rrun = rcur.next_run()
        while lrun is not None and rrun is not None:
            if lrun.is_null_key:
                yield from self._on_left_unmatched(lrun, left_outer)
                lrun = lcur.next_run()
                continue
            if rrun.is_null_key:
                yield from self._on_right_unmatched(rrun, right_outer)
                rrun = rcur.next_run()
                continue
            cmp = _run_key_cmp(lrun.key, rrun.key)
            if cmp < 0:
                yield from self._on_left_unmatched(lrun, left_outer)
                lrun = lcur.next_run()
            elif cmp > 0:
                yield from self._on_right_unmatched(rrun, right_outer)
                rrun = rcur.next_run()
            else:
                yield from self._on_match(lrun, rrun, left_outer,
                                          right_outer)
                lrun = lcur.next_run()
                rrun = rcur.next_run()
        while lrun is not None:
            yield from self._on_left_unmatched(lrun, left_outer)
            lrun = lcur.next_run()
        while rrun is not None:
            yield from self._on_right_unmatched(rrun, right_outer)
            rrun = rcur.next_run()

    def _on_left_unmatched(self, run: _Run, left_outer: bool
                           ) -> Iterator[pa.RecordBatch]:
        JT = self.JT
        jt = self.join_type
        if jt == JT.LEFT_ANTI:
            yield self._left_rows(run.table)
        elif jt == JT.EXISTENCE:
            yield self._left_rows(run.table, exists=False)
        elif left_outer:
            yield self._outer_left(run.table)

    def _on_right_unmatched(self, run: _Run, right_outer: bool
                            ) -> Iterator[pa.RecordBatch]:
        JT = self.JT
        jt = self.join_type
        if jt == JT.RIGHT_ANTI:
            yield self._right_rows_only(run.table)
        elif right_outer:
            yield self._outer_right(run.table)

    def _right_rows_only(self, tbl: pa.Table) -> pa.RecordBatch:
        arrays = [a.combine_chunks() for a in tbl.columns]
        return pa.RecordBatch.from_arrays(
            arrays, schema=self.out_schema.to_arrow())

    def _on_match(self, lrun: _Run, rrun: _Run, left_outer: bool,
                  right_outer: bool) -> Iterator[pa.RecordBatch]:
        JT = self.JT
        jt = self.join_type
        lt, rt = lrun.table, rrun.table
        ln, rn = lt.num_rows, rt.num_rows
        pair_emitting = jt in (JT.INNER, JT.LEFT, JT.RIGHT, JT.FULL)

        if self.join_filter is None:
            # equal keys: every pair matches — no expansion needed for
            # the row-level variants
            matched_l = np.ones(ln, dtype=bool)
            matched_r = np.ones(rn, dtype=bool)
            if pair_emitting:
                yield from self._emit_cross(lt, rt, None)
        else:
            # chunk the cross-product so a skewed hot key (huge ln*rn)
            # never materializes at once — the run may be exactly why the
            # hash join fell back here
            matched_l = np.zeros(ln, dtype=bool)
            matched_r = np.zeros(rn, dtype=bool)
            block = max(1, self._batch_rows // max(rn, 1))
            for ls in range(0, ln, block):
                le = min(ls + block, ln)
                l_idx = np.repeat(np.arange(ls, le, dtype=np.int64), rn)
                r_idx = np.tile(np.arange(rn, dtype=np.int64), le - ls)
                keep = self._filter_pairs(lt, rt, l_idx, r_idx)
                l_idx, r_idx = l_idx[keep], r_idx[keep]
                matched_l[l_idx] = True
                matched_r[r_idx] = True
                if pair_emitting:
                    for off in range(0, len(l_idx), self._batch_rows):
                        rb = self._emit_pairs(
                            lt, rt, l_idx[off:off + self._batch_rows],
                            r_idx[off:off + self._batch_rows])
                        if rb is not None:
                            yield self._project_out(rb)

        if jt == JT.LEFT_SEMI:
            rows = np.nonzero(matched_l)[0]
            if len(rows):
                yield self._left_rows(lt.take(pa.array(rows)))
            return
        if jt == JT.LEFT_ANTI:
            rows = np.nonzero(~matched_l)[0]
            if len(rows):
                yield self._left_rows(lt.take(pa.array(rows)))
            return
        if jt == JT.RIGHT_SEMI:
            rows = np.nonzero(matched_r)[0]
            if len(rows):
                yield self._right_rows_only(rt.take(pa.array(rows)))
            return
        if jt == JT.RIGHT_ANTI:
            rows = np.nonzero(~matched_r)[0]
            if len(rows):
                yield self._right_rows_only(rt.take(pa.array(rows)))
            return
        if jt == JT.EXISTENCE:
            arrays = [a.combine_chunks() for a in lt.columns] + \
                [pa.array(matched_l, type=pa.bool_())]
            yield pa.RecordBatch.from_arrays(
                arrays, schema=self.out_schema.to_arrow())
            return

        if left_outer:
            rows = np.nonzero(~matched_l)[0]
            if len(rows):
                yield self._outer_left(lt.take(pa.array(rows)))
        if right_outer:
            rows = np.nonzero(~matched_r)[0]
            if len(rows):
                yield self._outer_right(rt.take(pa.array(rows)))

    def _emit_cross(self, lt: pa.Table, rt: pa.Table, _unused
                    ) -> Iterator[pa.RecordBatch]:
        """Unfiltered run cross-product in batch-sized chunks."""
        ln, rn = lt.num_rows, rt.num_rows
        block = max(1, self._batch_rows // max(rn, 1))
        for ls in range(0, ln, block):
            le = min(ls + block, ln)
            l_idx = np.repeat(np.arange(ls, le, dtype=np.int64), rn)
            r_idx = np.tile(np.arange(rn, dtype=np.int64), le - ls)
            for off in range(0, len(l_idx), self._batch_rows):
                rb = self._emit_pairs(lt, rt,
                                      l_idx[off:off + self._batch_rows],
                                      r_idx[off:off + self._batch_rows])
                if rb is not None:
                    yield self._project_out(rb)
