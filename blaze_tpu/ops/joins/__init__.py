"""Joins (ref: datafusion-ext-plans/src/joins/ + broadcast_join_exec.rs)."""

from blaze_tpu.ops.joins.exec import (BaseJoinExec, BroadcastJoinExec,
                                      JoinMap, JoinType, ShuffledHashJoinExec,
                                      SortMergeJoinExec, build_join_map)

__all__ = ["BaseJoinExec", "BroadcastJoinExec", "JoinMap", "JoinType",
           "ShuffledHashJoinExec", "SortMergeJoinExec", "build_join_map"]
