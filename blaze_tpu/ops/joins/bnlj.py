"""Broadcast nested-loop join: non-equi joins over a broadcast side.

Parity: Spark's BroadcastNestedLoopJoinExec, which the reference gates
behind `auron.enable.bnlj` (SparkAuronConfiguration).  There is no keyed
probe: every probe row pairs with every build row through the condition,
chunked so the cross-product never materializes at once (same discipline
as the SMJ run merge)."""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np
import pyarrow as pa

from blaze_tpu import config
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.exprs import PhysicalExpr
from blaze_tpu.ops.base import BatchIterator, CoalesceStream, ExecutionPlan
from blaze_tpu.ops.joins.exec import JoinType, _null_out
from blaze_tpu.schema import BOOL, Field, Schema


class BroadcastNestedLoopJoinExec(ExecutionPlan):

    def __init__(self, left: ExecutionPlan, right: ExecutionPlan,
                 join_type: JoinType, build_side: str = "right",
                 join_filter: Optional[PhysicalExpr] = None,
                 existence_col: str = "exists",
                 broadcast_id: Optional[str] = None):
        super().__init__([left, right])
        assert build_side in ("left", "right")
        if join_type == JoinType.EXISTENCE and build_side != "right":
            # existence output carries LEFT rows + flag; probing the left
            # side requires the build on the right (Spark's BNLJ imposes
            # the same restriction)
            raise ValueError("existence BNLJ requires build_side='right'")
        self.join_type = join_type
        self.build_side = build_side
        self.join_filter = join_filter
        self._existence_col = existence_col
        # process-unique, never recycled (id(self) can be reused by a new
        # object and would hit a stale resource-map cache entry)
        from blaze_tpu.ops.joins.exec import _local_bid
        self._broadcast_id = broadcast_id or f"bnlj-{next(_local_bid)}"
        self._out_schema = self._build_schema()
        # matched-build state is shared across probe partitions (Spark
        # unions matchedBroadcastRows); the LAST partition to finish
        # emits the unmatched build rows
        import threading
        self._state_lock = threading.Lock()
        self._build_matched: Optional[np.ndarray] = None
        self._pending_partitions: Optional[set] = None

    def _build_schema(self) -> Schema:
        l, r = self.children[0].schema, self.children[1].schema
        jt = self.join_type
        if jt in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI):
            return l
        if jt in (JoinType.RIGHT_SEMI, JoinType.RIGHT_ANTI):
            return r
        if jt == JoinType.EXISTENCE:
            return Schema(list(l) + [Field(self._existence_col, BOOL,
                                           False)])
        fields = []
        for f in l:
            nullable = f.nullable or jt in (JoinType.RIGHT, JoinType.FULL)
            fields.append(Field(f.name, f.data_type, nullable))
        for f in r:
            nullable = f.nullable or jt in (JoinType.LEFT, JoinType.FULL)
            fields.append(Field(f.name, f.data_type, nullable))
        return Schema(fields)

    @property
    def schema(self) -> Schema:
        return self._out_schema

    @property
    def num_partitions(self) -> int:
        probe = 0 if self.build_side == "right" else 1
        return self.children[probe].num_partitions

    def _collect_build(self) -> pa.Table:
        from blaze_tpu.bridge.resource import get_or_create

        def factory() -> pa.Table:
            build = 1 if self.build_side == "right" else 0
            child = self.children[build]
            batches: List[pa.RecordBatch] = []
            for p in range(child.num_partitions):
                batches.extend(b.compact().to_arrow()
                               for b in child.execute(p))
            batches = [b for b in batches if b.num_rows]
            if not batches:
                return pa.Table.from_batches(
                    [], schema=child.schema.to_arrow())
            return pa.Table.from_batches(batches).combine_chunks()

        # built once per broadcast, shared by every probe partition
        # (the cached_build_hash_map pattern, broadcast_join_exec.rs:695)
        return get_or_create(f"bnlj://{self._broadcast_id}", factory)

    def execute(self, partition: int) -> BatchIterator:
        build_tbl = self._collect_build()
        probe_is_left = self.build_side == "right"
        probe = self.children[0 if probe_is_left else 1]
        with self._state_lock:
            if self._build_matched is None:
                self._build_matched = np.zeros(build_tbl.num_rows,
                                               dtype=bool)
                self._pending_partitions = set(range(self.num_partitions))
        build_matched = self._build_matched

        def gen():
            for batch in probe.execute(partition):
                batch = batch.compact()
                if batch.num_rows == 0:
                    continue
                yield from self._join_batch(batch.to_arrow(), build_tbl,
                                            build_matched, probe_is_left)
            with self._state_lock:
                self._pending_partitions.discard(partition)
                last = not self._pending_partitions
            if last:
                yield from self._emit_unmatched_build(
                    build_tbl, build_matched, probe_is_left)
        return iter(CoalesceStream(gen(), metrics=self.metrics))

    # ------------------------------------------------------------------
    def _pairs(self, probe_rb: pa.RecordBatch, build_tbl: pa.Table):
        """Chunked (p_idx, b_idx, keep) over the cross product."""
        pn, bn = probe_rb.num_rows, build_tbl.num_rows
        if bn == 0:
            return
        bs = config.BATCH_SIZE.get()
        block = max(1, bs // bn)
        for ps in range(0, pn, block):
            pe = min(ps + block, pn)
            p_idx = np.repeat(np.arange(ps, pe, dtype=np.int64), bn)
            b_idx = np.tile(np.arange(bn, dtype=np.int64), pe - ps)
            if self.join_filter is None:
                yield p_idx, b_idx
                continue
            rb = self._joined(probe_rb, build_tbl, p_idx, b_idx)
            cb = ColumnBatch.from_arrow(rb)
            keep = np.asarray(
                self.join_filter.evaluate(cb).as_mask(cb))[:rb.num_rows]
            yield p_idx[keep], b_idx[keep]

    def _joined(self, probe_rb, build_tbl, p_idx, b_idx) -> pa.RecordBatch:
        pt = probe_rb.take(pa.array(p_idx, type=pa.int64()))
        if build_tbl.num_rows:
            bt = build_tbl.take(pa.array(np.where(b_idx < 0, 0, b_idx),
                                         type=pa.int64()))
            bt_cols = [c.combine_chunks() for c in bt.columns]
            if (b_idx < 0).any():
                mask = b_idx < 0
                bt_cols = [_null_out(c, mask) for c in bt_cols]
        else:
            build_schema = self.children[
                1 if self.build_side == "right" else 0].schema
            bt_cols = [pa.nulls(len(b_idx), f.data_type.to_arrow())
                       for f in build_schema]
        probe_is_left = self.build_side == "right"
        left_cols = list(pt.columns) if probe_is_left else bt_cols
        right_cols = bt_cols if probe_is_left else list(pt.columns)
        l, r = self.children[0].schema, self.children[1].schema
        return pa.RecordBatch.from_arrays(
            [a.combine_chunks() if isinstance(a, pa.ChunkedArray) else a
             for a in left_cols + right_cols],
            schema=pa.schema([f.to_arrow() for f in l] +
                             [f.to_arrow() for f in r]))

    def _project_out(self, rb: pa.RecordBatch) -> ColumnBatch:
        out_arrow = self.schema.to_arrow()
        arrays = [col.cast(f.type, safe=False)
                  if not col.type.equals(f.type) else col
                  for col, f in zip(rb.columns, out_arrow)]
        out = pa.RecordBatch.from_arrays(arrays, schema=out_arrow)
        return ColumnBatch.from_arrow(out)

    def _join_batch(self, probe_rb, build_tbl, build_matched,
                    probe_is_left) -> Iterator[ColumnBatch]:
        jt = self.join_type
        pn = probe_rb.num_rows
        probe_matched = np.zeros(pn, dtype=bool)
        pair_emitting = jt in (JoinType.INNER, JoinType.LEFT,
                               JoinType.RIGHT, JoinType.FULL)
        for p_idx, b_idx in self._pairs(probe_rb, build_tbl):
            probe_matched[p_idx] = True
            build_matched[b_idx] = True
            if pair_emitting and len(p_idx):
                yield self._project_out(
                    self._joined(probe_rb, build_tbl, p_idx, b_idx))

        probe_semi = ((jt == JoinType.LEFT_SEMI and probe_is_left) or
                      (jt == JoinType.RIGHT_SEMI and not probe_is_left))
        probe_anti = ((jt == JoinType.LEFT_ANTI and probe_is_left) or
                      (jt == JoinType.RIGHT_ANTI and not probe_is_left))
        if probe_semi or probe_anti:
            keep = np.nonzero(probe_matched if probe_semi
                              else ~probe_matched)[0]
            if len(keep):
                yield ColumnBatch.from_arrow(
                    probe_rb.take(pa.array(keep, type=pa.int64())))
            return
        if jt == JoinType.EXISTENCE:
            arrays = list(probe_rb.columns) + \
                [pa.array(probe_matched, type=pa.bool_())]
            yield ColumnBatch.from_arrow(pa.RecordBatch.from_arrays(
                arrays, schema=self.schema.to_arrow()))
            return
        outer_probe = (jt == JoinType.FULL or
                       (jt == JoinType.LEFT and probe_is_left) or
                       (jt == JoinType.RIGHT and not probe_is_left))
        if outer_probe:
            un = np.nonzero(~probe_matched)[0]
            if len(un):
                yield self._project_out(self._joined(
                    probe_rb, build_tbl, un,
                    np.full(len(un), -1, dtype=np.int64)))

    def _emit_unmatched_build(self, build_tbl, build_matched,
                              probe_is_left) -> Iterator[ColumnBatch]:
        jt = self.join_type
        build_outer = (jt == JoinType.FULL or
                       (jt == JoinType.RIGHT and probe_is_left) or
                       (jt == JoinType.LEFT and not probe_is_left))
        build_semi = ((jt == JoinType.RIGHT_SEMI and probe_is_left) or
                      (jt == JoinType.LEFT_SEMI and not probe_is_left))
        build_anti = ((jt == JoinType.RIGHT_ANTI and probe_is_left) or
                      (jt == JoinType.LEFT_ANTI and not probe_is_left))
        if build_semi or build_anti:
            want = build_matched if build_semi else ~build_matched
            idx = np.nonzero(want)[0]
            if len(idx):
                rb = build_tbl.take(pa.array(idx, type=pa.int64())) \
                    .combine_chunks()
                yield ColumnBatch.from_arrow(rb.to_batches()[0])
            return
        if not build_outer or build_tbl.num_rows == 0:
            return
        idx = np.nonzero(~build_matched)[0]
        if not len(idx):
            return
        bt = build_tbl.take(pa.array(idx, type=pa.int64()))
        probe_schema = self.children[0 if probe_is_left else 1].schema
        null_probe = [pa.nulls(len(idx), f.data_type.to_arrow())
                      for f in probe_schema]
        bt_cols = [c.combine_chunks() for c in bt.columns]
        arrays = (null_probe + bt_cols) if probe_is_left else \
            (bt_cols + null_probe)
        rb = pa.RecordBatch.from_arrays(
            arrays, schema=pa.schema(
                [f.to_arrow() for f in self.children[0].schema] +
                [f.to_arrow() for f in self.children[1].schema]))
        yield self._project_out(rb)
