"""Equi-joins: sort-merge / shuffled-hash / broadcast, all join types.

Parity: sort_merge_join_exec.rs:397 + joins/smj/{full,semi,existence}_join.rs,
joins/join_hash_map.rs:277 JoinHashMap, broadcast_join_exec.rs:695 (SHJ and
BHJ share probe code), broadcast_join_build_hash_map_exec.rs (build map made
once per broadcast, cached via the resource map).

TPU-first redesign (SURVEY.md §7 step 6): instead of a pointer-chasing hash
map, the build side becomes a HASH-SORTED table: device xxhash64 over the
join keys, device sort by hash.  Probing is vectorized searchsorted over the
sorted hashes (binary search lowers to fused gathers), candidate pairs expand
host-side with numpy (data-dependent sizes live on host, the static-shape
boundary), and every candidate verifies actual key equality — hash collisions
cannot produce wrong results.  All three exec flavors share this probe core,
mirroring how the reference shares probe code between SHJ and BHJ.
"""

from __future__ import annotations

import enum
import itertools
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from blaze_tpu import config
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.xputil import asnp
from blaze_tpu.bridge.resource import get_or_create
from blaze_tpu.exprs import PhysicalExpr
from blaze_tpu.kernels import hashing as H
from blaze_tpu.ops.base import BatchIterator, CoalesceStream, ExecutionPlan
from blaze_tpu.schema import BOOL, Field, Schema, TypeId

# process-unique default broadcast ids (see BroadcastJoinExec.__init__)
_local_bid = itertools.count()


class JoinType(enum.Enum):
    INNER = "inner"
    LEFT = "left"            # left outer
    RIGHT = "right"          # right outer
    FULL = "full"
    LEFT_SEMI = "left_semi"
    LEFT_ANTI = "left_anti"
    RIGHT_SEMI = "right_semi"
    RIGHT_ANTI = "right_anti"
    EXISTENCE = "existence"  # left rows + bool `exists` column


import functools


from blaze_tpu.kernels.hashing import norm_float_keys as _norm_float_keys


@functools.lru_cache(maxsize=128)
def _hash_valid_jit(tids: Tuple[str, ...]):
    """One compiled program per key-type signature: chained xxhash64 +
    any-null mask (eagerly this is ~100 dispatches per batch and
    dominated the probe, like the partitioner before it was jitted)."""
    def f(flat_cols):
        flat_cols = _norm_float_keys(flat_cols, tids, jnp)
        cols = [(v, val, tid)
                for (v, val), tid in zip(flat_cols, tids)]
        h = H.hash_columns(cols, seed=42, xp=jnp, algo="xxhash64")
        anyn = None
        for (v, val) in flat_cols:
            nv = ~val
            anyn = nv if anyn is None else (anyn | nv)
        return h, anyn
    from blaze_tpu.bridge.xla_stats import meter_jit
    return meter_jit(f, name="join.hash_valid")


def _device_hash_keys(batch: ColumnBatch, key_exprs: Sequence[PhysicalExpr]
                      ) -> Tuple[np.ndarray, np.ndarray, List[pa.Array]]:
    """(hash int64[num_rows], any_null bool[num_rows], key arrays host).

    Host placement hashes in numpy directly — batches are unpadded there,
    and a jit per distinct batch length would recompile the ~60-op hash
    chain for every tail batch."""
    from blaze_tpu.bridge.placement import host_resident
    n = batch.num_rows
    on_host = host_resident()
    cap = n if on_host else batch.capacity
    flat_cols = []
    tids = []
    key_arrays = []
    for e in key_exprs:
        v = e.evaluate(batch)
        arr = v.to_host(n)
        key_arrays.append(arr)
        if v.is_device:
            data = asnp(v.data)[:cap] if on_host else v.data
            valid = asnp(v.validity)[:cap] if on_host else v.validity
            flat_cols.append((data, valid))
            tids.append(_tid(v.dtype))
        else:
            (mat, lengths), valid = H.string_column_to_padded_bytes(arr)
            # pad rows to capacity (lanes must line up with fixed-width
            # keys) and width to a pow2 bucket (bounded recompiles)
            w = max(4, 1 << (mat.shape[1] - 1).bit_length()) \
                if mat.shape[1] else 4
            full = np.zeros((cap, w), dtype=mat.dtype)
            full[:mat.shape[0], :mat.shape[1]] = mat
            full_len = np.zeros(cap, dtype=lengths.dtype)
            full_len[:len(lengths)] = lengths
            if on_host:
                flat_cols.append(((full, full_len), _pad(valid, cap)))
            else:
                flat_cols.append(((jnp.asarray(full),
                                   jnp.asarray(full_len)),
                                  jnp.asarray(_pad(valid, cap))))
            tids.append("utf8")
    if on_host:
        flat_cols = _norm_float_keys(flat_cols, tids, np)
        cols = [(v, val, tid) for (v, val), tid in zip(flat_cols, tids)]
        h_np = np.asarray(H.hash_columns(cols, seed=42, xp=np,
                                         algo="xxhash64"))
        anyn_np = np.zeros(cap, dtype=bool)
        for (_v, val) in flat_cols:
            anyn_np |= ~np.asarray(val)
        return h_np[:n], anyn_np[:n], key_arrays
    h, anyn = _hash_valid_jit(tuple(tids))(flat_cols)
    h_np, anyn_np = jax.device_get((h, anyn))
    return h_np[:n], anyn_np[:n].copy(), key_arrays


def promote_join_key_exprs(lkeys, rkeys, lschema, rschema):
    """Widen mismatched join-key expression pairs to a common numeric
    type (int/int -> int64, numeric mix -> float64) so every join path
    hashes/compares identical types — the murmur/xxhash probe hashes
    int32 and int64 of equal value differently.  Spark's analyzer
    inserts these casts during resolution; hand-built plans may not."""
    from blaze_tpu.exprs.cast import Cast
    from blaze_tpu.schema import FLOAT64, INT64
    out_l, out_r = [], []
    for le, re in zip(lkeys, rkeys):
        lt = le.data_type(lschema)
        rt = re.data_type(rschema)
        if lt.id == rt.id:
            out_l.append(le)
            out_r.append(re)
            continue
        if lt.is_integer and rt.is_integer:
            common = INT64
        elif ((lt.is_integer or lt.is_floating) and
              (rt.is_integer or rt.is_floating)):
            common = FLOAT64
        else:
            out_l.append(le)
            out_r.append(re)
            continue
        out_l.append(le if lt.id == common.id else Cast(le, common))
        out_r.append(re if rt.id == common.id else Cast(re, common))
    return out_l, out_r


def _pad(v: np.ndarray, n: int) -> np.ndarray:
    if len(v) == n:
        return v
    out = np.zeros(n, dtype=v.dtype)
    out[:len(v)] = v
    return out


def _tid(dtype) -> str:
    return dtype.id.value


class JoinMap:
    """Hash-sorted build table (the JoinHashMap analog, join_hash_map.rs:277).

    Probe lookups run through one of two vectorized paths:
      * device (accelerator placement): kernels/join.py — jit'd binary
        search + scan-based bounded pair expansion, one scalar sync per
        batch (ref verdict: no per-batch host loops);
      * host placement: Arrow's C++ hash table (pc.index_in) over the
        unique build hashes + run-length expansion in numpy.
    """

    def __init__(self, table: pa.Table, key_exprs: Sequence[PhysicalExpr],
                 schema: Schema):
        self.table = table.combine_chunks()
        self.schema = schema
        self._key_exprs = list(key_exprs)
        self._built = False
        self.matched = np.zeros(self.table.num_rows, dtype=bool)

    def _ensure_index(self) -> None:
        """Hash-sort the build side on first probe.  Lazy because the
        Acero host path and the null-aware-anti empty-probe cases never
        touch the hash index at all."""
        if self._built:
            return
        from blaze_tpu.kernels.join import build_runs
        n = self.table.num_rows
        if n:
            cb = ColumnBatch.from_arrow(self.table)
            hashes, any_null, self.key_arrays = _device_hash_keys(
                cb, self._key_exprs)
            # null keys never match: a reserved hash bucket we skip
            self._valid = ~any_null
            order = np.argsort(hashes, kind="stable")
            self.sorted_hashes = hashes[order]
            # slot arrays narrow to i32 below 2^31 build rows — keeps
            # the probe's gather indices off TPU 64-bit emulation
            self.sorted_idx = (order.astype(np.int32)
                               if n < (1 << 31) else order)
            self.uh, self.ustart, self.ucount = build_runs(self.sorted_hashes)
            self._uh_pa = pa.array(self.uh, type=pa.int64())
        else:
            self._valid = np.zeros(0, dtype=bool)
            self.sorted_hashes = np.zeros(0, dtype=np.int64)
            self.sorted_idx = np.zeros(0, dtype=np.int32)
            self.uh = np.zeros(0, dtype=np.int64)
            self.ustart = np.zeros(0, dtype=np.int32)
            self.ucount = np.zeros(0, dtype=np.int32)
            self.key_arrays = []
        self._built = True

    @property
    def num_rows(self) -> int:
        return self.table.num_rows

    @property
    def has_null_keys(self) -> bool:
        self._ensure_index()
        return bool((~self._valid).any())

    def lookup(self, probe_hashes: np.ndarray, probe_null: np.ndarray,
               probe_keys: List[pa.Array]
               ) -> Tuple[np.ndarray, np.ndarray]:
        """Candidate-verified (probe_idx, build_idx) pair arrays."""
        n = len(probe_hashes)
        if self.num_rows == 0 or n == 0:
            return (np.zeros(0, dtype=np.int64),) * 2
        self._ensure_index()
        from blaze_tpu.bridge.placement import host_resident
        if host_resident():
            probe_idx, build_idx = self._lookup_host(probe_hashes,
                                                     probe_null)
        else:
            from blaze_tpu.kernels.join import probe_expand_device
            import jax.numpy as _j
            probe_idx, build_idx = probe_expand_device(
                _j.asarray(self.uh), _j.asarray(self.ustart),
                _j.asarray(self.ucount), self.sorted_idx,
                _j.asarray(probe_hashes), _j.asarray(probe_null))
        if not len(probe_idx):
            return (np.zeros(0, dtype=np.int64),) * 2
        # drop null-key build rows, then verify true equality per key
        # column (NaN == NaN for float keys: Spark join-key semantics)
        keep = self._valid[build_idx]
        for pk, bk in zip(probe_keys, self.key_arrays):
            if not keep.any():
                break
            pe = pk.take(pa.array(probe_idx, type=pa.int64()))
            be = bk.take(pa.array(build_idx, type=pa.int64()))
            eq = pc.equal(pe, be).fill_null(False)
            if pa.types.is_floating(pe.type):
                eq = pc.or_(eq, pc.and_(pc.is_nan(pe), pc.is_nan(be)))
                eq = eq.fill_null(False)
            keep &= np.asarray(eq)
        return probe_idx[keep], build_idx[keep]

    def _lookup_host(self, probe_hashes: np.ndarray, probe_null: np.ndarray
                     ) -> Tuple[np.ndarray, np.ndarray]:
        """Arrow C++ hash-table lookup (GIL-releasing) + numpy run
        expansion — replaces two numpy searchsorted passes that measured
        ~3 ms per 72K-row batch each."""
        ui = pc.index_in(pa.array(probe_hashes, type=pa.int64()),
                         value_set=self._uh_pa)
        ui_np = np.asarray(ui.fill_null(len(self.uh)), dtype=np.int64)
        hit = (ui_np < len(self.uh)) & ~probe_null
        lo = np.where(hit, self.ustart[np.minimum(ui_np, len(self.uh) - 1)],
                      0)
        counts = np.where(hit, self.ucount[np.minimum(ui_np,
                                                      len(self.uh) - 1)], 0)
        total = int(counts.sum())
        if total == 0:
            return (np.zeros(0, dtype=np.int64),) * 2
        n = len(probe_hashes)
        probe_idx = np.repeat(np.arange(n, dtype=np.int64), counts)
        starts = np.repeat(lo, counts)
        offs = np.arange(total, dtype=np.int64) - \
            np.repeat(np.cumsum(counts) - counts, counts)
        build_idx = self.sorted_idx[starts + offs]
        return probe_idx, build_idx


def build_join_map(batches: Iterator[pa.RecordBatch], schema: Schema,
                   key_exprs: Sequence[PhysicalExpr]) -> JoinMap:
    blist = list(batches)
    table = (pa.Table.from_batches(blist) if blist
             else pa.Table.from_batches([], schema=schema.to_arrow()))
    return JoinMap(table, key_exprs, schema)


class BaseJoinExec(ExecutionPlan):
    """Shared probe core.  `build_side` names which child is materialized."""

    def __init__(self, left: ExecutionPlan, right: ExecutionPlan,
                 left_keys: Sequence[PhysicalExpr],
                 right_keys: Sequence[PhysicalExpr],
                 join_type: JoinType,
                 build_side: str = "right",
                 join_filter: Optional[PhysicalExpr] = None,
                 existence_col: str = "exists",
                 null_aware_anti: bool = False):
        super().__init__([left, right])
        assert build_side in ("left", "right")
        # widen mismatched key pairs ONCE here so every probe path —
        # Acero one-shot, streaming run cursors, device hash probe —
        # sees identical key types (Spark's analyzer inserts these casts;
        # hand-built plans may not).  The cached broadcast build-map path
        # (BuildHashMapExec) still relies on the upstream cast guarantee:
        # its map is hashed before this node exists.
        self.left_keys, self.right_keys = promote_join_key_exprs(
            list(left_keys), list(right_keys), left.schema, right.schema)
        self.join_type = join_type
        self.build_side = build_side
        self.join_filter = join_filter
        self._existence_col = existence_col
        # NOT IN subquery semantics (ref BroadcastJoinExecNode
        # is_null_aware_anti_join): a NULL anywhere makes membership
        # three-valued UNKNOWN, so null build keys reject everything and
        # null probe keys never pass
        self.null_aware_anti = null_aware_anti
        self._out_schema = self._build_schema()

    # -- schema -------------------------------------------------------------
    def _build_schema(self) -> Schema:
        l, r = self.children[0].schema, self.children[1].schema
        jt = self.join_type
        if jt in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI):
            return l
        if jt in (JoinType.RIGHT_SEMI, JoinType.RIGHT_ANTI):
            return r
        if jt == JoinType.EXISTENCE:
            return Schema(list(l) + [Field(self._existence_col, BOOL, False)])
        fields = []
        for f in l:
            nullable = f.nullable or jt in (JoinType.RIGHT, JoinType.FULL)
            fields.append(Field(f.name, f.data_type, nullable))
        for f in r:
            nullable = f.nullable or jt in (JoinType.LEFT, JoinType.FULL)
            fields.append(Field(f.name, f.data_type, nullable))
        return Schema(fields)

    @property
    def schema(self) -> Schema:
        return self._out_schema

    @property
    def num_partitions(self) -> int:
        probe = 0 if self.build_side == "right" else 1
        return self.children[probe].num_partitions

    # -- build-side acquisition (overridden by BroadcastJoinExec) ----------
    def _get_join_map(self, partition: int) -> JoinMap:
        build = 1 if self.build_side == "right" else 0
        child = self.children[build]
        stream = (b.compact().to_arrow() for b in child.execute(partition))
        keys = self.right_keys if build == 1 else self.left_keys
        return build_join_map(stream, child.schema, keys)

    # -- execution ----------------------------------------------------------
    def execute(self, partition: int) -> BatchIterator:
        return self._probe_with_map(self._get_join_map(partition),
                                    partition)

    def _probe_with_map(self, jmap: "JoinMap", partition: int
                        ) -> BatchIterator:
        probe_is_left = self.build_side == "right"
        probe = self.children[0 if probe_is_left else 1]
        probe_keys = self.left_keys if probe_is_left else self.right_keys

        from blaze_tpu.bridge.placement import host_resident
        if host_resident() and self._pa_join_eligible():
            # host placement: Arrow's C++ hash join (Acero, GIL-releasing,
            # all cores) is the host-engine analog of the reference's
            # native probe (join_hash_map.rs:277); the jit'd probe kernels
            # (kernels/join.py) stay the device path
            return iter(CoalesceStream(
                self._pa_join(jmap, partition, probe, probe_keys,
                              probe_is_left),
                metrics=self.metrics))
        return iter(CoalesceStream(
            self._stream_probe(jmap, probe.execute(partition), probe_keys,
                               probe_is_left),
            metrics=self.metrics))

    def _stream_probe(self, jmap, batches, probe_keys, probe_is_left):
        """Incremental vectorized probe: the build index is hashed once,
        batches stream through lookup (bounded memory)."""
        for batch in batches:
            batch = batch.compact()
            if batch.num_rows == 0:
                continue
            yield from self._probe_batch(jmap, batch, probe_keys,
                                         probe_is_left)
        yield from self._emit_unmatched_build(jmap, probe_is_left)

    # -- host placement: Arrow C++ (Acero) hash join -----------------------
    _PA_JOIN_TYPES = {
        JoinType.INNER: "inner",
        JoinType.LEFT: "left outer",
        JoinType.RIGHT: "right outer",
        JoinType.FULL: "full outer",
        JoinType.LEFT_SEMI: "left semi",
        JoinType.LEFT_ANTI: "left anti",
        JoinType.RIGHT_SEMI: "right semi",
        JoinType.RIGHT_ANTI: "right anti",
    }

    def _pa_join_eligible(self) -> bool:
        # residual filters and NOT-IN null semantics keep the shared
        # vectorized probe; EXISTENCE has no Acero equivalent
        return (self.join_filter is None and not self.null_aware_anti
                and self.join_type in self._PA_JOIN_TYPES)

    def _join_key_table(self, plan_schema: Schema, rb_or_tbl, keys,
                        prefix: str):
        """Rename columns positionally ({prefix}{i}) and append computed
        join-key columns (__k{i}) so arbitrary key exprs and duplicate
        names across sides both work.  Float keys normalize -0.0 -> 0.0
        and NaN -> one canonical pattern (Acero hashes raw bits; Spark's
        NormalizeFloatingNumbers runs upstream of the join)."""
        from blaze_tpu.exprs.base import BoundReference
        tbl = (pa.Table.from_batches([rb_or_tbl])
               if isinstance(rb_or_tbl, pa.RecordBatch) else rb_or_tbl)
        n = tbl.num_rows
        cb = None
        key_cols = []
        for e in keys:
            if isinstance(e, BoundReference):
                arr = tbl.column(e.index)  # zero-copy; no batch rebuild
                if isinstance(arr, pa.ChunkedArray):
                    arr = arr.combine_chunks()
            else:
                if cb is None:
                    cb = ColumnBatch.from_arrow(tbl.combine_chunks())
                arr = e.evaluate(cb).to_host(n)
            if pa.types.is_floating(arr.type):
                arr = pc.add(arr, 0.0)  # -0.0 + 0.0 == +0.0
                nan = pa.scalar(float("nan"), type=arr.type)
                arr = pc.if_else(pc.is_nan(arr), nan, arr)
            key_cols.append(arr)
        arrays = list(tbl.columns) + key_cols
        names = [f"{prefix}{i}" for i in range(tbl.num_columns)] + \
            [f"__{prefix}k{i}" for i in range(len(keys))]
        return pa.table(arrays, names=names)

    def _pa_join(self, jmap: JoinMap, partition: int, probe, probe_keys,
                 probe_is_left: bool) -> Iterator[ColumnBatch]:
        """One Acero join over the collected probe side.  If the probe
        exceeds the collect budget, switch to the streaming JoinMap probe
        instead of re-running Acero per chunk — Acero rebuilds its
        build-side hash table on every Table.join call, while JoinMap
        hashes the build side exactly once."""
        limit = config.FUSED_HOST_COLLECT_ROWS.get()
        build_is_left = not probe_is_left
        build_keys = self.left_keys if build_is_left else self.right_keys
        build_tbl = self._join_key_table(
            jmap.schema, jmap.table, build_keys,
            "l" if build_is_left else "r")
        # the build side is materialized BEFORE probe collection, so the
        # join-key runtime filter applies DURING collection: probe rows
        # outside the build key range never occupy collect memory (and a
        # selective filter keeps large probes under the collect limit
        # instead of tipping them onto the streaming path)
        prefilter, covered, rf_ranges = self._collect_prefilter(
            build_tbl, probe_keys, probe_is_left)
        prune_pred = self._scan_prune_pred(probe, rf_ranges)
        chunks: List[pa.RecordBatch] = []
        rows = 0
        # Arrow-resident collection: sources that hold Arrow data (scans)
        # stream it straight through without a ColumnBatch round trip;
        # parquet probes additionally row-group-prune by the runtime
        # filter for THIS read only
        stream = (probe.arrow_batches(partition, extra_prune=prune_pred)
                  if prune_pred is not None
                  else probe.arrow_batches(partition))
        overflowed = False
        for rb in stream:
            if prefilter is not None and rb.num_rows:
                rb = prefilter(rb)
            if rb.num_rows == 0:
                continue
            chunks.append(rb)
            rows += rb.num_rows
            if rows >= limit:
                overflowed = True
                break
        if overflowed:
            yield from self._stream_probe(
                jmap,
                (ColumnBatch.from_arrow(b) for b in
                 itertools.chain(chunks, stream)),
                probe_keys, probe_is_left)
            return
        yield from self._pa_join_once(build_tbl, chunks, probe_keys,
                                      probe_is_left, skip_filter_keys=covered)

    @staticmethod
    def _scan_prune_pred(probe, rf_ranges):
        """Build-side join-key [min, max] runtime filter as a
        scan-granularity pruning predicate for the probe's parquet scan —
        with date-clustered fact tables whole row groups outside the
        build key range are never decoded (the reference pushes its bloom
        runtime filters into the probe scan the same way:
        bloom_filter_might_contain.rs + parquet page filtering).
        Row-exact filtering still happens in the collect prefilter; the
        predicate is handed to ONE arrow_batches read (never stored on
        the shared plan node).  None when inapplicable."""
        from blaze_tpu.exprs.base import BoundReference, Literal
        from blaze_tpu.exprs.binary import BinaryExpr
        from blaze_tpu.ops.scan import ParquetScanExec
        if (not rf_ranges or not isinstance(probe, ParquetScanExec)
                or probe._out_partition_fields
                or not config.PARQUET_ENABLE_PAGE_FILTERING.get()):
            return None
        pred = None
        for _k, idx, mn, mx in rf_ranges:
            if idx >= len(probe.schema):
                continue
            f = probe.schema[idx]
            col = BoundReference(idx, f.name)
            rng = BinaryExpr(
                "and",
                BinaryExpr(">=", col, Literal(mn.as_py(), f.data_type)),
                BinaryExpr("<=", col, Literal(mx.as_py(), f.data_type)))
            pred = rng if pred is None else BinaryExpr("and", pred, rng)
        return pred

    def _runtime_filter_drop_ok(self, probe_is_left: bool) -> bool:
        """Whether dropping never-matching probe rows is semantics-
        preserving: inner joins and probe-side semi joins only."""
        jt = self.join_type
        return (jt == JoinType.INNER or
                (jt == JoinType.LEFT_SEMI and probe_is_left) or
                (jt == JoinType.RIGHT_SEMI and not probe_is_left))

    @staticmethod
    def _range_mask(col, mn, mx):
        return pc.and_(pc.greater_equal(col, mn), pc.less_equal(col, mx))

    def _collect_prefilter(self, build_tbl, probe_keys,
                           probe_is_left: bool):
        """(closure, covered-keys) pair: the closure drops probe rows
        outside the build side's integer join-key [min, max] ranges,
        applied batch-by-batch while the probe is being collected;
        `covered` lists the key positions it handled so the join-time
        filter skips them; `ranges` [(key, probe_col, min, max)] lets the
        caller push scan-granularity pruning.  (None, frozenset(), [])
        when inapplicable (non-droppable join type, computed/non-integer
        keys)."""
        none = (None, frozenset(), [])
        if not (self._runtime_filter_drop_ok(probe_is_left)
                and config.JOIN_RUNTIME_FILTER_ENABLE.get()):
            return none
        bprefix = "l" if not probe_is_left else "r"
        ranges = []
        empty = build_tbl.num_rows == 0
        if not empty:
            from blaze_tpu.exprs.base import BoundReference
            for i, e in enumerate(probe_keys):
                if not isinstance(e, BoundReference):
                    continue
                bcol = build_tbl.column(f"__{bprefix}k{i}")
                if not pa.types.is_integer(bcol.type):
                    continue
                mm = pc.min_max(bcol)
                if not mm["min"].is_valid:
                    empty = True  # all-null build keys: nothing matches
                    break
                ranges.append((i, e.index, mm["min"], mm["max"]))
        metrics = self.metrics
        if empty:
            def drop_all(rb):
                metrics.add("runtime_filter_pruned", rb.num_rows)
                return rb.slice(0, 0)
            return drop_all, frozenset(range(len(probe_keys))), []
        if not ranges:
            return none

        def apply(rb):
            mask = None
            for _k, idx, mn, mx in ranges:
                m = self._range_mask(rb.column(idx), mn, mx)
                mask = m if mask is None else pc.and_kleene(mask, m)
            out = rb.filter(mask)
            metrics.add("runtime_filter_pruned",
                        rb.num_rows - out.num_rows)
            return out
        return apply, frozenset(k for k, *_r in ranges), ranges

    def _runtime_filter_probe(self, build_tbl, probe_tbl, pprefix: str,
                              probe_is_left: bool,
                              skip_keys: frozenset = frozenset()):
        """Join-key runtime filter: before probing, drop probe rows whose
        integer key falls outside the build side's [min, max] — the
        engine-side analog of the reference's runtime-filter joins
        (bloom_filter agg + bloom_filter_might_contain.rs pushed into the
        probe scan).  One vectorized comparison pass over the probe
        replaces hash-probing every row that cannot possibly match.

        Only join types where a non-matching probe row produces no output
        may drop rows (inner, probe-side semi); null keys never match an
        equi-join, so the null-dropping comparison semantics are exact."""
        if (not self._runtime_filter_drop_ok(probe_is_left)
                or not config.JOIN_RUNTIME_FILTER_ENABLE.get()
                or probe_tbl.num_rows == 0):
            return probe_tbl
        if build_tbl.num_rows == 0:
            return probe_tbl.slice(0, 0)  # inner/semi vs empty build
        bprefix = "r" if pprefix == "l" else "l"
        for i in range(len(self.left_keys)):
            if i in skip_keys:  # already pruned during probe collection
                continue
            bcol = build_tbl.column(f"__{bprefix}k{i}")
            if not pa.types.is_integer(bcol.type):
                continue
            mm = pc.min_max(bcol)
            if not mm["min"].is_valid:
                probe_tbl = probe_tbl.slice(0, 0)  # all-null build keys
                break
            before = probe_tbl.num_rows
            probe_tbl = probe_tbl.filter(self._range_mask(
                probe_tbl.column(f"__{pprefix}k{i}"),
                mm["min"], mm["max"]))
            self.metrics.add("runtime_filter_pruned",
                             before - probe_tbl.num_rows)
            if probe_tbl.num_rows == 0:
                break
        return probe_tbl

    # span cap for the direct-address build table (slots are int64:
    # 32 MB at the cap) and a density floor so sparse key sets still
    # take the hash join
    _DIRECT_SPAN_MAX = 1 << 22
    _DIRECT_BUILD_MAX = 1 << 20

    def _direct_join_once(self, build_tbl, probe_tbl, probe_is_left):
        """Single-integer-key join via a DIRECT-ADDRESS table.

        Dimension keys in star schemas (date_sk, item_sk, store_sk...)
        are dense contiguous ranges; Acero re-hashes the build side on
        every Table.join call, while a slot array indexed by `key - min`
        resolves each probe row with one subtract + one gather — the
        same dense-key strategy the fused aggregation uses
        (plan/fused.py dense group ids).  Applies to probe-driven join
        types with a UNIQUE build key (each probe row matches at most
        one build row, so output needs no pair expansion).  Returns a
        joined table shaped exactly like the Acero result (l{i}/r{i}
        columns), or None -> Acero fallback.
        """
        jt = self.join_type
        eligible = {JoinType.INNER}
        if probe_is_left:
            eligible |= {JoinType.LEFT, JoinType.LEFT_SEMI,
                         JoinType.LEFT_ANTI}
        else:
            eligible |= {JoinType.RIGHT, JoinType.RIGHT_SEMI,
                         JoinType.RIGHT_ANTI}
        if jt not in eligible or len(self.left_keys) != 1:
            return None
        pprefix = "l" if probe_is_left else "r"
        bprefix = "r" if probe_is_left else "l"
        bk = build_tbl.column(f"__{bprefix}k0")
        pk = probe_tbl.column(f"__{pprefix}k0")
        if not (pa.types.is_integer(bk.type) and
                pa.types.is_integer(pk.type)):
            return None
        if any(pa.types.is_unsigned_integer(t) and t.bit_width == 64
               for t in (bk.type, pk.type)):
            # uint64 beyond int64 range wraps in the astype(int64)
            # below; a wrapped PROBE value could silently false-match
            # an in-range build key, so both sides are rejected
            return None
        if build_tbl.num_rows > self._DIRECT_BUILD_MAX:
            return None
        bk = bk.combine_chunks() if isinstance(bk, pa.ChunkedArray) else bk
        pk = pk.combine_chunks() if isinstance(pk, pa.ChunkedArray) else pk
        bnp = bk.drop_null().to_numpy(zero_copy_only=False).astype(
            np.int64, copy=False)
        b_rows = (np.flatnonzero(bk.is_valid().to_numpy(
            zero_copy_only=False)) if bk.null_count
            else np.arange(len(bnp)))
        n_probe_cols = probe_tbl.num_columns - 1
        n_build_cols = build_tbl.num_columns - 1
        probe_cols = probe_tbl.columns[:n_probe_cols]
        probe_names = probe_tbl.column_names[:n_probe_cols]
        build_cols = build_tbl.columns[:n_build_cols]
        build_names = build_tbl.column_names[:n_build_cols]
        semi_anti = jt in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI,
                           JoinType.RIGHT_SEMI, JoinType.RIGHT_ANTI)
        anti = jt in (JoinType.LEFT_ANTI, JoinType.RIGHT_ANTI)
        outer = jt in (JoinType.LEFT, JoinType.RIGHT)
        if bnp.size == 0:
            if anti or outer:
                b = np.full(probe_tbl.num_rows, -1, np.int64)
                match = np.zeros(probe_tbl.num_rows, bool)
            else:
                # fast-path engagement stays observable on this branch
                self.metrics.add("direct_join_rows", 0)
                return pa.table(
                    [c.slice(0, 0) for c in probe_cols] +
                    ([] if semi_anti else
                     [c.slice(0, 0) for c in build_cols]),
                    names=probe_names +
                    ([] if semi_anti else build_names))
        else:
            mn = int(bnp.min())
            mx = int(bnp.max())
            span = mx - mn + 1
            if span > self._DIRECT_SPAN_MAX or (
                    span > 65536 and span > 64 * bnp.size):
                # span cap + density floor: a sparse key set would pay
                # an O(span) slot array to serve few build rows
                return None
            slot = np.full(span, -1, np.int64)
            slot[bnp - mn] = b_rows
            # uniqueness: a duplicate key overwrites its first slot, so
            # the number of occupied slots betrays duplicates in O(span)
            if int((slot >= 0).sum()) != bnp.size:
                return None
            if pk.null_count:
                pvalid = pk.is_valid().to_numpy(zero_copy_only=False)
                pnp = pk.fill_null(0).to_numpy(
                    zero_copy_only=False).astype(np.int64, copy=False)
            else:
                pvalid = None
                pnp = pk.to_numpy(zero_copy_only=False).astype(
                    np.int64, copy=False)
            # range-test BEFORE subtracting: comparisons are exact while
            # pnp - mn can wrap int64 for extreme key ranges (a wrapped
            # index landing in [0, span) would be a silent false match);
            # clipping first keeps the subtraction in-bounds, and filled
            # nulls (0) are masked by pvalid regardless of range
            inr = (pnp >= mn) & (pnp <= mx)
            if pvalid is not None:
                inr &= pvalid
            idx = np.clip(pnp, mn, mx) - mn
            b = np.where(inr, slot[idx], np.int64(-1))
            match = b >= 0
        if semi_anti:
            sel = np.flatnonzero(~match if anti else match)
            tbl = pa.table(probe_cols, names=probe_names)
            self.metrics.add("direct_join_rows", len(sel))
            return tbl.take(pa.array(sel))
        if outer:
            p_sel = None  # every probe row survives
            b_idx = pa.array(b, mask=~match)
        else:  # inner
            p_sel = np.flatnonzero(match)
            b_idx = pa.array(b[match])
        ptbl = pa.table(probe_cols, names=probe_names)
        if p_sel is not None:
            ptbl = ptbl.take(pa.array(p_sel))
        taken = [pc.take(c, b_idx) for c in build_cols]
        arrays = list(ptbl.columns) + taken
        names = list(probe_names) + list(build_names)
        if not probe_is_left:
            arrays = taken + list(ptbl.columns)
            names = list(build_names) + list(probe_names)
        self.metrics.add("direct_join_rows", len(b_idx))
        return pa.table(arrays, names=names)

    def _pa_join_once(self, build_tbl, probe_chunks, probe_keys,
                      probe_is_left: bool,
                      skip_filter_keys: frozenset = frozenset()
                      ) -> Iterator[ColumnBatch]:
        probe_schema = self.children[0 if probe_is_left else 1].schema
        pprefix = "l" if probe_is_left else "r"
        if probe_chunks:
            probe_pa = pa.Table.from_batches(probe_chunks)
        else:
            probe_pa = pa.Table.from_batches(
                [], schema=probe_schema.to_arrow())
        probe_tbl = self._join_key_table(probe_schema, probe_pa,
                                         probe_keys, pprefix)
        probe_tbl = self._runtime_filter_probe(build_tbl, probe_tbl,
                                               pprefix, probe_is_left,
                                               skip_keys=skip_filter_keys)
        joined = self._direct_join_once(build_tbl, probe_tbl,
                                        probe_is_left)
        if joined is None:
            left_tbl = probe_tbl if probe_is_left else build_tbl
            right_tbl = build_tbl if probe_is_left else probe_tbl
            lk = [f"__lk{i}" for i in range(len(self.left_keys))]
            rk = [f"__rk{i}" for i in range(len(self.right_keys))]
            joined = left_tbl.join(
                right_tbl, keys=lk, right_keys=rk,
                join_type=self._PA_JOIN_TYPES[self.join_type],
                use_threads=True)
        out_arrow = self.schema.to_arrow()
        jt = self.join_type
        if jt in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI):
            names = [f"l{i}"
                     for i in range(len(self.children[0].schema))]
        elif jt in (JoinType.RIGHT_SEMI, JoinType.RIGHT_ANTI):
            names = [f"r{i}"
                     for i in range(len(self.children[1].schema))]
        else:
            names = [f"l{i}"
                     for i in range(len(self.children[0].schema))] + \
                    [f"r{i}" for i in range(len(self.children[1].schema))]
        arrays = []
        for name, f in zip(names, out_arrow):
            col = joined.column(name)
            if isinstance(col, pa.ChunkedArray):
                col = col.combine_chunks()
            if not col.type.equals(f.type):
                col = col.cast(f.type, safe=False)
            arrays.append(col)
        rb = pa.RecordBatch.from_arrays(arrays, schema=out_arrow)
        bs = config.BATCH_SIZE.get()
        for off in range(0, rb.num_rows, bs):
            yield ColumnBatch.from_arrow(
                rb.slice(off, min(bs, rb.num_rows - off)))

    # -- probe one batch ----------------------------------------------------
    def _probe_batch(self, jmap: JoinMap, batch: ColumnBatch,
                     probe_keys: Sequence[PhysicalExpr], probe_is_left: bool
                     ) -> Iterator[ColumnBatch]:
        n = batch.num_rows
        hashes, any_null, key_arrays = _device_hash_keys(batch, probe_keys)
        p_idx, b_idx = jmap.lookup(hashes, any_null, key_arrays)
        probe_rb = batch.to_arrow()

        if self.join_filter is not None and len(p_idx):
            mask = self._apply_filter(probe_rb, jmap, p_idx, b_idx,
                                      probe_is_left)
            p_idx, b_idx = p_idx[mask], b_idx[mask]

        jt = self.join_type
        jmap.matched[b_idx] = True
        match_count = np.bincount(p_idx, minlength=n)

        probe_semi = ((jt == JoinType.LEFT_SEMI and probe_is_left) or
                      (jt == JoinType.RIGHT_SEMI and not probe_is_left))
        probe_anti = ((jt == JoinType.LEFT_ANTI and probe_is_left) or
                      (jt == JoinType.RIGHT_ANTI and not probe_is_left))
        if probe_anti and self.null_aware_anti and jmap.num_rows:
            if jmap.has_null_keys:
                return  # NULL in the IN-list: nothing ever qualifies
            # NOT IN over a non-empty list: a NULL probe key is UNKNOWN.
            # (empty build side falls through: x NOT IN () is TRUE even
            # for NULL x, so the plain anti path below keeps every row)
            keep = np.nonzero((match_count == 0) & ~any_null)[0]
            if len(keep):
                yield ColumnBatch.from_arrow(
                    probe_rb.take(pa.array(keep, type=pa.int64())))
            return
        if probe_semi or probe_anti:
            keep = np.nonzero(match_count > 0 if probe_semi
                              else match_count == 0)[0]
            if len(keep):
                yield ColumnBatch.from_arrow(
                    probe_rb.take(pa.array(keep, type=pa.int64())))
            return
        if jt in (JoinType.LEFT_SEMI, JoinType.RIGHT_SEMI,
                  JoinType.LEFT_ANTI, JoinType.RIGHT_ANTI):
            # semi/anti of the BUILD side: probe only records matches;
            # emission happens in _emit_unmatched_build
            return
        if jt == JoinType.EXISTENCE:
            arrays = list(probe_rb.columns) + \
                [pa.array(match_count > 0, type=pa.bool_())]
            yield ColumnBatch.from_arrow(pa.RecordBatch.from_arrays(
                arrays, schema=self.schema.to_arrow()))
            return

        # inner/outer: matched pairs
        outer_probe = (jt == JoinType.FULL or
                       (jt == JoinType.LEFT and probe_is_left) or
                       (jt == JoinType.RIGHT and not probe_is_left))
        if outer_probe:
            un = np.nonzero(match_count == 0)[0]
            if len(un):
                p_idx = np.concatenate([p_idx, un])
                b_idx = np.concatenate([b_idx,
                                        np.full(len(un), -1, dtype=np.int64)])
        if not len(p_idx):
            return
        yield self._materialize(probe_rb, jmap, p_idx, b_idx, probe_is_left)

    def _apply_filter(self, probe_rb, jmap: JoinMap, p_idx, b_idx,
                      probe_is_left) -> np.ndarray:
        joined = self._joined_batch(probe_rb, jmap, p_idx, b_idx,
                                    probe_is_left, allow_missing=False)
        v = self.join_filter.evaluate(joined)
        return np.asarray(v.as_mask(joined))[:joined.num_rows]

    def _joined_batch(self, probe_rb, jmap, p_idx, b_idx, probe_is_left,
                      allow_missing=True) -> ColumnBatch:
        pt = probe_rb.take(pa.array(p_idx, type=pa.int64()))
        bi = pa.array(b_idx, type=pa.int64())
        if jmap.num_rows == 0:
            bt_cols = [pa.nulls(len(b_idx), f.data_type.to_arrow())
                       for f in jmap.schema]
        elif allow_missing and (b_idx < 0).any():
            bi = pa.array(np.where(b_idx < 0, 0, b_idx), type=pa.int64())
            bt = jmap.table.take(bi)
            null_mask = b_idx < 0
            bt_cols = [_null_out(c, null_mask) for c in bt.columns]
        else:
            bt = jmap.table.take(bi)
            bt_cols = [c.combine_chunks() if isinstance(c, pa.ChunkedArray)
                       else c for c in bt.columns]
        left_cols = (list(pt.columns) if probe_is_left else bt_cols)
        right_cols = (bt_cols if probe_is_left else list(pt.columns))
        arrays = left_cols + right_cols
        out_schema = self.schema if self.join_type in (
            JoinType.INNER, JoinType.LEFT, JoinType.RIGHT, JoinType.FULL) \
            else Schema(list(self.children[0].schema) +
                        list(self.children[1].schema))
        arrays = [a.combine_chunks() if isinstance(a, pa.ChunkedArray) else a
                  for a in arrays]
        rb = pa.RecordBatch.from_arrays(
            [a.cast(f.data_type.to_arrow(), safe=False)
             if not a.type.equals(f.data_type.to_arrow()) else a
             for a, f in zip(arrays, out_schema)],
            schema=out_schema.to_arrow())
        return ColumnBatch.from_arrow(rb)

    def _materialize(self, probe_rb, jmap, p_idx, b_idx, probe_is_left
                     ) -> ColumnBatch:
        return self._joined_batch(probe_rb, jmap, p_idx, b_idx, probe_is_left)

    def _emit_unmatched_build(self, jmap: JoinMap, probe_is_left: bool
                              ) -> Iterator[ColumnBatch]:
        jt = self.join_type
        build_outer = (jt == JoinType.FULL or
                       (jt == JoinType.RIGHT and probe_is_left) or
                       (jt == JoinType.LEFT and not probe_is_left))
        build_semi = ((jt == JoinType.RIGHT_SEMI and probe_is_left) or
                      (jt == JoinType.LEFT_SEMI and not probe_is_left))
        build_anti = ((jt == JoinType.RIGHT_ANTI and probe_is_left) or
                      (jt == JoinType.LEFT_ANTI and not probe_is_left))
        if build_semi or build_anti:
            want = jmap.matched if build_semi else ~jmap.matched
            idx = np.nonzero(want)[0]
            if len(idx):
                rb = jmap.table.take(pa.array(idx, type=pa.int64())) \
                    .combine_chunks()
                yield ColumnBatch.from_arrow(rb.to_batches()[0])
            return
        if not build_outer or jmap.num_rows == 0:
            return
        idx = np.nonzero(~jmap.matched)[0]
        if not len(idx):
            return
        bt = jmap.table.take(pa.array(idx, type=pa.int64()))
        probe_schema = self.children[0 if probe_is_left else 1].schema
        null_probe = [pa.nulls(len(idx), f.data_type.to_arrow())
                      for f in probe_schema]
        bt_cols = [c.combine_chunks() if isinstance(c, pa.ChunkedArray) else c
                   for c in bt.columns]
        arrays = (null_probe + bt_cols) if probe_is_left else \
            (bt_cols + null_probe)
        rb = pa.RecordBatch.from_arrays(arrays, schema=self.schema.to_arrow())
        yield ColumnBatch.from_arrow(rb)


def _null_out(col, null_mask: np.ndarray) -> pa.Array:
    col = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
    return pc.if_else(pa.array(~null_mask), col,
                      pa.nulls(len(col), col.type))


class SortMergeJoinExec(BaseJoinExec):
    """Streaming merge join (ref sort_merge_join_exec.rs:397 +
    joins/smj/*, joins/stream_cursor.rs).

    Children are consumed key-sorted (ascending, nulls first).  A child
    that is already a SortExec on the join keys streams straight through
    (the converter contract — childOrderingRequiredTag — guarantees sorts
    in translated plans); otherwise a spillable SortExec is inserted, so
    hand-built plans stay correct and the sort inherits the external-sort
    memory discipline."""

    def _sorted_child(self, side: int) -> ExecutionPlan:
        from blaze_tpu.ops.sort import SortExec
        child = self.children[side]
        keys = self.left_keys if side == 0 else self.right_keys
        if isinstance(child, SortExec):
            specs = child._specs
            if len(specs) >= len(keys) and all(
                    s[0].cache_key() == k.cache_key() and not s[1] and s[2]
                    for s, k in zip(specs, keys)):
                return child
        return SortExec(child, [(k, False, True) for k in keys])

    def _acero_sorted(self, partition: int):
        """Materialized host path: both sides within the collect budget
        join through Arrow's C++ hash join, and the OUTPUT re-sorts by
        the join keys (ascending, nulls first) to preserve SMJ's
        output-ordering contract for downstream consumers.  Returns None
        — falling back to the streaming run-cursor merge — when a side
        overflows the budget (the spillable path exists precisely for
        that), keys are computed expressions, or Acero lacks the join
        type.  A run-cursor merge over N one-row key runs is O(N)
        Python; this path replaces it with two vectorized passes (the
        q97 distinct-pair FULL OUTER was 200x slower streaming)."""
        from blaze_tpu.bridge.placement import host_resident
        from blaze_tpu.exprs.base import BoundReference
        if (not host_resident() or not self._pa_join_eligible()
                or not config.SMJ_ACERO_ENABLE.get()):
            return None  # EXISTENCE is already outside _PA_JOIN_TYPES
        if not all(isinstance(k, BoundReference)
                   for k in self.left_keys + self.right_keys):
            return None
        limit = config.FUSED_HOST_COLLECT_ROWS.get()
        sides = []
        for i in (0, 1):
            chunks, rows = [], 0
            stream = self.children[i].arrow_batches(partition)
            for rb in stream:
                if rb.num_rows == 0:
                    continue
                chunks.append(rb)
                rows += rb.num_rows
                if rows > limit:
                    # hand everything consumed so far back to execute():
                    # when child output is already key-sorted, the
                    # streaming merge resumes from these chunks without
                    # re-reading the input
                    return ("overflow", i, sides, chunks, stream)
            sides.append(chunks)
        build_tbl = self._join_key_table(
            self.children[1].schema,
            (pa.Table.from_batches(sides[1]) if sides[1]
             else pa.Table.from_batches(
                 [], schema=self.children[1].schema.to_arrow())),
            self.right_keys, "r")

        def gen():
            out = list(self._pa_join_once(build_tbl, sides[0],
                                          self.left_keys, True))
            if not out:
                return
            tbl = pa.Table.from_batches(
                [cb.compact().to_arrow() for cb in out])
            order = self._smj_output_order(tbl)
            if order is not None:
                tbl = tbl.take(order)
            bs = config.BATCH_SIZE.get()
            for off in range(0, tbl.num_rows, bs):
                yield ColumnBatch.from_arrow(
                    tbl.slice(off, min(bs, tbl.num_rows - off))
                    .combine_chunks())
        return gen()

    def _smj_output_order(self, tbl):
        """Sort indices restoring key order (nulls first).  Key columns
        live at the BoundReference positions of whichever side(s) the
        output carries; FULL/RIGHT joins coalesce left/right keys (the
        unmatched side's key is null)."""
        jt = self.join_type
        nl = len(self.children[0].schema)
        if jt in (JoinType.RIGHT_SEMI, JoinType.RIGHT_ANTI):
            keys = [tbl.column(k.index) for k in self.right_keys]
        elif jt in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI,
                    JoinType.INNER, JoinType.LEFT):
            keys = [tbl.column(k.index) for k in self.left_keys]
        elif jt in (JoinType.RIGHT, JoinType.FULL):
            keys = [pc.coalesce(tbl.column(lk.index),
                                tbl.column(nl + rk.index))
                    for lk, rk in zip(self.left_keys, self.right_keys)]
        else:
            return None
        kt = pa.table(keys, names=[f"k{i}" for i in range(len(keys))])
        return pc.sort_indices(
            kt, sort_keys=[(f"k{i}", "ascending")
                           for i in range(len(keys))],
            null_placement="at_start")

    def execute(self, partition: int) -> BatchIterator:
        from blaze_tpu.ops.joins.smj import MergeJoiner, _RunCursor
        acero = self._acero_sorted(partition)
        l_stream = r_stream = None
        if isinstance(acero, tuple):
            # collect-budget overflow on side i.  If every side whose
            # data was already consumed is ALREADY key-sorted (children
            # are SortExecs in translated plans), resume the streaming
            # merge from the buffered chunks — no re-read; otherwise
            # fall through to full re-execution (a fresh SortExec would
            # have to see all rows anyway).
            _tag, i, done, part_chunks, rest = acero
            consumed = list(range(i + 1))
            if all(self._sorted_child(j) is self.children[j]
                   for j in consumed):
                chained = itertools.chain(part_chunks, rest)
                if i == 0:
                    l_stream = chained
                else:
                    l_stream = iter(done[0])
                    r_stream = chained
            acero = None
        if acero is not None:
            # output_rows is counted inside _pa_join_once already
            return iter(acero)

        def arrow_stream(plan):
            for b in plan.execute(partition):
                rb = b.compact().to_arrow()
                if rb.num_rows:
                    yield rb

        if l_stream is None:
            l_stream = arrow_stream(self._sorted_child(0))
        if r_stream is None:
            r_stream = arrow_stream(self._sorted_child(1))

        joiner = MergeJoiner(self.children[0].schema,
                             self.children[1].schema, self.schema,
                             self.join_type, self.join_filter,
                             self._existence_col)
        lcur = _RunCursor(l_stream, self.left_keys,
                          self.children[0].schema)
        rcur = _RunCursor(r_stream, self.right_keys,
                          self.children[1].schema)

        def gen():
            for rb in joiner.join(lcur, rcur):
                yield ColumnBatch.from_arrow(rb)
        return iter(CoalesceStream(gen(), metrics=self.metrics))


class ShuffledHashJoinExec(BaseJoinExec):
    """SHJ parity node: build side = one shuffled partition.  When
    `auron.smjfallback.enable` is set and the build side exceeds the
    rows/bytes thresholds while materializing, the partition re-executes
    as a streaming sort-merge join (ref smjfallback confs,
    SparkAuronConfiguration.java:231-250)."""

    def execute(self, partition: int) -> BatchIterator:
        if not config.SMJ_FALLBACK_ENABLE.get():
            yield from super().execute(partition)
            return
        build = 1 if self.build_side == "right" else 0
        child = self.children[build]
        row_cap = config.SMJ_FALLBACK_ROWS_THRESHOLD.get()
        mem_cap = config.SMJ_FALLBACK_MEM_THRESHOLD.get()
        batches: List[pa.RecordBatch] = []
        rows = nbytes = 0
        overflowed = False
        for b in child.execute(partition):
            rb = b.compact().to_arrow()
            if rb.num_rows == 0:
                continue
            batches.append(rb)
            rows += rb.num_rows
            nbytes += rb.nbytes
            if rows > row_cap or nbytes > mem_cap:
                overflowed = True
                break
        if overflowed:
            # abandon the hash build; re-run this partition as SMJ
            self.metrics.add("smj_fallback", 1)
            del batches
            smj = SortMergeJoinExec(
                self.children[0], self.children[1], self.left_keys,
                self.right_keys, self.join_type,
                build_side=self.build_side, join_filter=self.join_filter,
                existence_col=self._existence_col,
                null_aware_anti=self.null_aware_anti)
            smj.metrics = self.metrics
            yield from smj.execute(partition)
            return
        keys = self.right_keys if build == 1 else self.left_keys
        jmap = build_join_map(iter(batches), child.schema, keys)
        yield from self._probe_with_map(jmap, partition)


class BroadcastJoinExec(BaseJoinExec):
    """BHJ: build side materialized once per broadcast and cached in the
    resource map (ref broadcast_join_exec.rs:695 cached_build_hash_map)."""

    def __init__(self, *args, broadcast_id: Optional[str] = None, **kw):
        super().__init__(*args, **kw)
        # default ids must be process-unique FOREVER, not id(self):
        # CPython reuses freed addresses, and a recycled id would serve a
        # stale build map out of the long-lived resource-map cache
        self._broadcast_id = broadcast_id or f"bhj-{next(_local_bid)}"

    def _get_join_map(self, partition: int) -> JoinMap:
        build = 1 if self.build_side == "right" else 0
        child = self.children[build]

        def factory():
            keys = self.right_keys if build == 1 else self.left_keys
            batches = []
            for p in range(child.num_partitions):
                batches.extend(b.compact().to_arrow()
                               for b in child.execute(p))
            return build_join_map(iter(batches), child.schema, keys)
        # the cache key folds the build-side output schema: plan rewrites
        # (column pruning) may narrow the build columns per consumer, and
        # two plans sharing one broadcast_id must not serve each other
        # positionally-different build tables
        sig = ",".join(f.name for f in child.schema)
        return get_or_create(
            f"join_map://{self._broadcast_id}/{hash(sig) & 0xffffffff:x}",
            factory)


class BuildHashMapExec(ExecutionPlan):
    """Broadcast build-map stage (ref broadcast_join_build_hash_map_exec.rs):
    materializes the build side once per broadcast so downstream
    BroadcastJoinExec tasks can share it through the resource-map cache.
    Batches stream through unchanged; the map is built as a side effect the
    first time any consumer pulls the stage."""

    def __init__(self, child: ExecutionPlan, keys: Sequence[PhysicalExpr],
                 cache_id: Optional[str] = None):
        super().__init__([child])
        self.keys = list(keys)
        self.cache_id = cache_id

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self, partition: int) -> BatchIterator:
        child = self.children[0]
        if not self.cache_id:  # no consumer to share with: stream through
            yield from child.execute(partition)
            return
        batches = [b.compact() for b in child.execute(partition)]
        arrow = [b.to_arrow() for b in batches]
        get_or_create(
            f"join_map://{self.cache_id}",
            lambda: build_join_map(iter(arrow), child.schema, self.keys))
        yield from iter(batches)
