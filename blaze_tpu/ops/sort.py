"""External merge-sort operator.

Parity: sort_exec.rs:86 — key-prefix `Rows` encoding + in-memory radix sort +
multi-level spills + LoserTree k-way merge, as a spill-aware MemConsumer
(sort_exec.rs:375-390).

TPU-first redesign:
  * in-memory runs sort ON DEVICE via the order-key encoding +
    `lax.sort` (kernels/compare.py) — XLA's fused lexicographic sort is the
    radix-sort replacement;
  * runs that exceed the memory budget spill as sorted Arrow runs through
    the shared Spill tiers;
  * the k-way merge is BATCH-vectorized on host (numpy lexsort over u64
    order keys), not a row-at-a-time loser tree: every round computes the
    safe threshold (min over runs of the run-head's max key) and merges all
    rows <= threshold in one vectorized sort — same asymptotics, no
    per-row Python.
  * string sort keys are object arrays of raw UTF-8 `bytes` (byte order
    == code-point order == Spark's binary string ordering); descending
    maps through a 256-entry invert table at C speed per row.
"""

from __future__ import annotations

import heapq
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from blaze_tpu import config
from blaze_tpu.batch import ColumnBatch, DeviceColumn, bucket_capacity
from blaze_tpu.exprs import PhysicalExpr
from blaze_tpu.memory import MemConsumer, MemManager, Spill, try_new_spill
from blaze_tpu.ops.base import BatchIterator, ExecutionPlan
from blaze_tpu.schema import Schema, TypeId

SortSpec = Tuple[PhysicalExpr, bool, bool]  # (expr, descending, nulls_first)


# ---------------------------------------------------------------------------
# host order keys (merge + string-key sorting)
# ---------------------------------------------------------------------------

def _host_order_key(arr: pa.Array, descending: bool, nulls_first: bool
                    ) -> List[np.ndarray]:
    """[bucket u8, key] columns whose joint lexicographic order equals SQL
    order; key is u64 for numerics (sign-biased / IEEE-flipped) or <U for
    strings.  Mirrors kernels/compare.order_key for the host."""
    n = len(arr)
    valid = np.ones(n, dtype=bool) if arr.null_count == 0 else \
        np.asarray(arr.is_valid())
    t = arr.type
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        bucket = np.where(valid, 2, 0 if nulls_first else 4).astype(np.uint8)
        return [bucket] + _string_sort_keys(arr, descending)
    if pa.types.is_floating(t):
        f = np.asarray(arr.fill_null(0.0), dtype=np.float64)
        nan = np.isnan(f)
        f = np.where(nan, 0.0, f) + 0.0
        bits = f.view(np.uint64)
        key = np.where(f < 0, ~bits, bits | np.uint64(1 << 63))
        if descending:
            key = ~key
        bucket = np.where(nan, 1 if descending else 3, 2).astype(np.uint8)
    elif pa.types.is_boolean(t):
        key = np.asarray(arr.fill_null(False)).astype(np.uint64)
        if descending:
            key = np.uint64(1) - key
        bucket = np.full(n, 2, dtype=np.uint8)
    elif pa.types.is_decimal(t):
        # Order by the UNSCALED two's-complement int128 (value casting to
        # int64 would truncate fractional digits).  Key = sign-biased high
        # u64 + low u64, matching the device order-key path's unscaled-int
        # encoding (schema.py:36) but exact for any precision.
        filled = arr.fill_null(0).cast(pa.decimal128(38, t.scale))
        buf = filled.buffers()[1]
        off = filled.offset
        u = np.frombuffer(buf, dtype=np.uint64,
                          count=2 * (off + n))[2 * off:]
        lokey = u[0::2].copy()
        hikey = u[1::2].copy() ^ np.uint64(1 << 63)
        if descending:
            hikey, lokey = ~hikey, ~lokey
        bucket = np.where(valid, 2, 0 if nulls_first else 4).astype(np.uint8)
        hikey = np.where(valid, hikey, np.uint64(0))
        lokey = np.where(valid, lokey, np.uint64(0))
        return [bucket, hikey, lokey]
    else:
        if pa.types.is_timestamp(t) or pa.types.is_date(t):
            arr2 = arr.cast(pa.int64() if pa.types.is_timestamp(t) else pa.int32())
        else:
            arr2 = arr
        v = np.asarray(arr2.fill_null(0)).astype(np.int64)
        key = v.view(np.uint64) ^ np.uint64(1 << 63)
        if descending:
            key = ~key
        bucket = np.full(n, 2, dtype=np.uint8)
    bucket = np.where(valid, bucket, 0 if nulls_first else 4).astype(np.uint8)
    key = np.where(valid, key, np.zeros_like(key)) if key.dtype != object else key
    return [bucket, key]


_INVERT_TABLE = bytes(255 - i for i in range(256))


def _string_sort_keys(arr: pa.Array, descending: bool) -> List[np.ndarray]:
    """UTF-8 bytewise sort keys as ONE object column of `bytes` (fixed
    arity, so k-way merge can compare keys across batches).  Byte order
    equals code-point order in UTF-8, so this matches Spark's string
    comparison.  Descending maps every string through a 256-entry invert
    table plus an 0xFF sentinel — C-speed per row, no per-character
    Python (VERDICT r1 weak #5)."""
    bin_t = (pa.large_binary() if pa.types.is_large_string(arr.type)
             else pa.binary())
    raw = arr.cast(bin_t).fill_null(b"").to_pylist()
    key = np.empty(len(raw), dtype=object)
    key[:] = ([b.translate(_INVERT_TABLE) + b"\xff" for b in raw]
              if descending else raw)
    return [key]


def host_sort_keys(rb: pa.RecordBatch, key_cols: Sequence[int],
                   descending: Sequence[bool], nulls_first: Sequence[bool]
                   ) -> List[np.ndarray]:
    keys: List[np.ndarray] = []
    for ci, desc, nf in zip(key_cols, descending, nulls_first):
        keys.extend(_host_order_key(rb.column(ci), desc, nf))
    return keys


def lexsort_host(keys: List[np.ndarray]) -> np.ndarray:
    # np.lexsort sorts by the LAST key first
    return np.lexsort(tuple(reversed(keys)))


# ---------------------------------------------------------------------------
# the operator
# ---------------------------------------------------------------------------

class SortExec(ExecutionPlan, MemConsumer):

    def __init__(self, child: ExecutionPlan, sort_specs: Sequence[SortSpec],
                 fetch: Optional[int] = None):
        ExecutionPlan.__init__(self, [child])
        MemConsumer.__init__(self, "SortExec")
        self._specs = list(sort_specs)
        self._fetch = fetch

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self, partition: int) -> BatchIterator:
        state = _SortState(self, self.schema, self._specs)
        state.set_spillable(MemManager.get())
        try:
            for batch in self.children[0].execute(partition):
                state.insert(batch)
            out_rows = 0
            for rb in state.merged_output():
                if self._fetch is not None:
                    if out_rows >= self._fetch:
                        break
                    if out_rows + rb.num_rows > self._fetch:
                        rb = rb.slice(0, self._fetch - out_rows)
                out_rows += rb.num_rows
                yield ColumnBatch.from_arrow(rb)
        finally:
            state.unregister()

    # MemConsumer interface is delegated to the per-execution state; SortExec
    # itself registers nothing (execute() may run per partition concurrently)
    def spill(self) -> int:
        return 0


class _SortState(MemConsumer):
    """Per-partition sort state: staged batches + spilled sorted runs."""

    def __init__(self, op: SortExec, schema: Schema, specs: Sequence[SortSpec]):
        super().__init__("sort")
        self._op = op
        self.metrics = op.metrics
        self._schema = schema
        self._specs = specs
        self._staged: List[pa.RecordBatch] = []
        self._staged_bytes = 0
        self._spills: List[Spill] = []
        # sort keys are evaluated through exprs on the ColumnBatch, then
        # carried as extra leading columns in the staged arrow batches so
        # spilled runs keep their keys (the Rows-encoding analog)
        self._num_keys = len(specs)

    # -- ingest -------------------------------------------------------------
    def insert(self, batch: ColumnBatch) -> None:
        rb = self._with_key_columns(batch)
        if rb.num_rows == 0:
            return
        self._staged.append(rb)
        self._staged_bytes += rb.nbytes
        self.update_mem_used(self._staged_bytes)

    def _with_key_columns(self, batch: ColumnBatch) -> pa.RecordBatch:
        """Evaluate sort exprs; prepend as __key{i} columns to the payload."""
        arrays = []
        names = []
        n = batch.num_rows
        for i, (expr, _, _) in enumerate(self._specs):
            v = expr.evaluate(batch)
            arrays.append(v.to_host(n))
            names.append(f"__key{i}")
        payload = batch.to_arrow()
        sel = None
        if batch.selection is not None:
            sel = np.asarray(batch.row_mask())[:n]
            arrays = [a.filter(pa.array(sel)) for a in arrays]
        for name, col in zip(self._schema.names, payload.columns):
            arrays.append(col)
            names.append(name)
        return pa.RecordBatch.from_arrays(arrays, names=names)

    # -- spilling (MemConsumer) --------------------------------------------
    def spill(self) -> int:
        if not self._staged:
            return 0
        run = self._sort_staged()
        spill = try_new_spill()
        spill.write_batches(iter(run))
        self._spills.append(spill)
        released = self._staged_bytes
        self._staged = []
        self._staged_bytes = 0
        self._mem_used = 0
        self.spill_metrics.spill_count += 1
        self.spill_metrics.spilled_bytes += released
        self._op.metrics.add("spill_count")
        self._op.metrics.add("spilled_bytes", released)
        return released

    def _sort_staged(self) -> List[pa.RecordBatch]:
        if not self._staged:
            return []
        tbl = pa.Table.from_batches(self._staged).combine_chunks()
        rb = tbl.to_batches()[0] if tbl.num_rows else None
        if rb is None:
            return []
        perm = self._sort_permutation(rb)
        sorted_rb = rb.take(pa.array(perm, type=pa.int64()))
        bs = config.BATCH_SIZE.get()
        return [sorted_rb.slice(i, min(bs, sorted_rb.num_rows - i))
                for i in range(0, sorted_rb.num_rows, bs)]

    def _sort_permutation(self, rb: pa.RecordBatch) -> np.ndarray:
        key_cols = list(range(self._num_keys))
        desc = [d for _, d, _ in self._specs]
        nf = [f for _, _, f in self._specs]
        fixed = all(_is_fixed(rb.column(i).type) for i in key_cols)
        if fixed and rb.num_rows >= 1024:
            # device path: order keys + fused lax.sort
            import jax.numpy as jnp
            from blaze_tpu.kernels import compare
            from blaze_tpu.schema import DataType
            cols = []
            for i in key_cols:
                dc = DeviceColumn.from_arrow(
                    rb.column(i), DataType.from_arrow(rb.column(i).type),
                    bucket_capacity(rb.num_rows))
                cols.append((dc.data, dc.validity, dc.dtype))
            keys = compare.order_keys(cols, desc, nf)
            valid = jnp.arange(cols[0][0].shape[0]) < rb.num_rows
            perm = compare.lexsort_indices(keys, valid)
            return np.asarray(perm)[:rb.num_rows]
        keys = host_sort_keys(rb, key_cols, desc, nf)
        return lexsort_host(keys)

    # -- merged output ------------------------------------------------------
    def merged_output(self) -> Iterator[pa.RecordBatch]:
        in_mem = self._sort_staged()
        runs: List[Iterator[pa.RecordBatch]] = []
        if in_mem:
            runs.append(iter(in_mem))
        for s in self._spills:
            runs.append(s.read_batches())
        if not runs:
            return
        if len(runs) == 1:
            for rb in runs[0]:
                yield self._strip_keys(rb)
            return
        yield from self._merge_runs(runs)

    def _strip_keys(self, rb: pa.RecordBatch) -> pa.RecordBatch:
        cols = [rb.column(i) for i in range(self._num_keys, rb.num_columns)]
        return pa.RecordBatch.from_arrays(cols, schema=self._schema.to_arrow())

    def _merge_runs(self, runs: List[Iterator[pa.RecordBatch]]
                    ) -> Iterator[pa.RecordBatch]:
        desc = [d for _, d, _ in self._specs]
        nf = [f for _, _, f in self._specs]
        for rb in merge_sorted_batches(runs, list(range(self._num_keys)),
                                       desc, nf):
            yield self._strip_keys(rb)


def merge_sorted_batches(runs: List[Iterator[pa.RecordBatch]],
                         key_cols: Sequence[int], desc: Sequence[bool],
                         nf: Sequence[bool]) -> Iterator[pa.RecordBatch]:
    """Vectorized k-way merge of sorted batch streams (shared by SortExec
    and the agg spill merge): per round, merge every buffered row whose key
    <= the smallest 'run-head max key' (safe threshold — no unbuffered row
    can precede it) in one host lexsort instead of a row-at-a-time loser
    tree (ref algorithm/loser_tree.rs)."""
    heads: List[Optional[pa.RecordBatch]] = []
    keys: List[Optional[List[np.ndarray]]] = []
    for r in runs:
        rb = next(r, None)
        heads.append(rb)
        keys.append(host_sort_keys(rb, key_cols, desc, nf) if rb is not None
                    else None)

    def _advance(i):
        rb = next(runs[i], None)
        heads[i] = rb
        keys[i] = (host_sort_keys(rb, key_cols, desc, nf)
                   if rb is not None else None)

    bs = config.BATCH_SIZE.get()
    while True:
        live = [i for i in range(len(runs)) if heads[i] is not None]
        if not live:
            return
        if len(live) == 1:
            i = live[0]
            yield heads[i]
            _advance(i)
            continue
        # threshold = min over live runs of that run's head LAST key
        # (each run is sorted, so its head's last row is its max)
        last_tuples = {i: _key_tuple(keys[i], heads[i].num_rows - 1)
                       for i in live}
        t_i = min(live, key=lambda i: last_tuples[i])
        threshold = last_tuples[t_i]
        take_parts: List[pa.RecordBatch] = []
        take_keys: List[List[np.ndarray]] = []
        for i in live:
            k = keys[i]
            cnt = _count_leq(k, threshold)
            if cnt == 0:
                continue
            take_parts.append(heads[i].slice(0, cnt))
            take_keys.append([col[:cnt] for col in k])
            if cnt == heads[i].num_rows:
                _advance(i)
            else:
                heads[i] = heads[i].slice(cnt)
                keys[i] = [col[cnt:] for col in keys[i]]
        merged = pa.Table.from_batches(take_parts).combine_chunks()
        mk = [np.concatenate([tk[j] for tk in take_keys])
              for j in range(len(take_keys[0]))]
        perm = lexsort_host(mk)
        out = merged.to_batches()[0].take(pa.array(perm, type=pa.int64()))
        # chunk by rows AND by the suggested merge memory target
        # (ref auron.suggested.batch.memSize.multiwayMerging)
        mem_target = config.SUGGESTED_MERGING_BATCH_MEM_SIZE.get()
        row_bytes = max(1, out.nbytes // max(1, out.num_rows))
        chunk = max(1, min(bs, mem_target // row_bytes))
        for off in range(0, out.num_rows, chunk):
            yield out.slice(off, min(chunk, out.num_rows - off))


def _is_fixed(t: pa.DataType) -> bool:
    return not (pa.types.is_string(t) or pa.types.is_large_string(t) or
                pa.types.is_binary(t) or pa.types.is_nested(t))


def _key_tuple(keys: List[np.ndarray], row: int) -> tuple:
    return tuple(k[row] for k in keys)


def compare_scalar(k: np.ndarray, t):
    """Wrap a comparison scalar so numpy never coerces it: a raw `bytes`
    against an object array becomes S-dtype and silently LOSES trailing
    NUL bytes, making a row neither < nor == its own threshold."""
    if k.dtype == object:
        w = np.empty((), dtype=object)
        w[()] = t
        return w
    return t


def _count_leq(keys: List[np.ndarray], threshold: tuple) -> int:
    """Rows at the front of this sorted run with key <= threshold
    (lexicographic), vectorized."""
    n = len(keys[0])
    # lexicographic <=: build from the last key backwards
    leq = np.ones(n, dtype=bool)
    for j in range(len(keys) - 1, -1, -1):
        k = keys[j]
        t = compare_scalar(k, threshold[j])
        leq = (k < t) | ((k == t) & leq)
    # run is sorted so leq is a prefix; count via argmin trick
    return int(leq.sum())
