"""Window operator: rank family, lead/lag, nth_value, agg-over-window.

Parity: window_exec.rs:896 + window/window_context.rs:31 +
window/processors/{row_number,rank,dense_rank,percent_rank,cume_dist,lead,
nth_value,agg}.rs and window-group-limit (proto auron.proto:600).

TPU-first: the input arrives sorted by (partition keys, order keys) —
Spark plans a SortExec under every window — so all processors become
vectorized prefix scans over segment structure: partition boundaries ->
cumsum segment ids, rank = position of the last order-key change, running
aggregates = segmented cumulative sums.  No per-row state machine; one
fused device pass per batch set.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from blaze_tpu import config
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.memory import MemConsumer, try_new_spill
from blaze_tpu.exprs import PhysicalExpr
from blaze_tpu.ops.base import BatchIterator, ExecutionPlan
from blaze_tpu.ops.sort import host_sort_keys
from blaze_tpu.schema import (DataType, Field, FLOAT64, INT32, INT64, Schema, TypeId)


class WindowRankType(enum.Enum):
    ROW_NUMBER = "row_number"
    RANK = "rank"
    DENSE_RANK = "dense_rank"
    PERCENT_RANK = "percent_rank"
    CUME_DIST = "cume_dist"


@dataclass
class WindowFunc:
    name: str

    def out_field(self, in_schema: Schema) -> Field:
        raise NotImplementedError


@dataclass
class RankFunc(WindowFunc):
    kind: WindowRankType = WindowRankType.ROW_NUMBER

    def out_field(self, in_schema):
        if self.kind in (WindowRankType.PERCENT_RANK, WindowRankType.CUME_DIST):
            return Field(self.name, FLOAT64, False)
        return Field(self.name, INT32, False)


@dataclass
class LeadLagFunc(WindowFunc):
    expr: PhysicalExpr = None
    offset: int = 1          # positive = lead, negative = lag
    default: Optional[object] = None

    def out_field(self, in_schema):
        return Field(self.name, self.expr.data_type(in_schema), True)


@dataclass
class NthValueFunc(WindowFunc):
    expr: PhysicalExpr = None
    n: int = 1               # 1-based
    ignore_nulls: bool = False  # ref processors/nth_value.rs IGNORE NULLS

    def out_field(self, in_schema):
        return Field(self.name, self.expr.data_type(in_schema), True)


@dataclass
class WindowAggFunc(WindowFunc):
    agg: object = None       # AggFunction
    running: bool = True     # unbounded-preceding..current-row vs whole part

    def out_field(self, in_schema):
        return Field(self.name, self.agg.output_type(in_schema), True)


class _WindowBuffer(MemConsumer):
    """Buffered window input rows: a spill-capable MemConsumer (same
    pattern as ops/sort.py _SortState).  Under memory pressure the
    in-memory batches move to the shared Spill tiers (host-RAM -> disk)
    and are read back at the next boundary flush."""

    def __init__(self, op: "WindowExec"):
        super().__init__("WindowExec.buffer")
        self._op = op
        self.metrics = op.metrics
        self._mem: List[pa.RecordBatch] = []
        self._mem_bytes = 0
        self._spills: list = []
        self.rows = 0

    def add(self, rb: pa.RecordBatch) -> None:
        self._mem.append(rb)
        self._mem_bytes += rb.nbytes
        self.rows += rb.num_rows
        self.update_mem_used(self._mem_bytes)

    def spill(self) -> int:
        if not self._mem:
            return 0
        s = try_new_spill()
        s.write_batches(iter(self._mem))
        self._spills.append(s)
        released = self._mem_bytes
        self._mem = []
        self._mem_bytes = 0
        self._mem_used = 0
        self.spill_metrics.spill_count += 1
        self.spill_metrics.spilled_bytes += released
        self._op.metrics.add("spill_count")
        self._op.metrics.add("spilled_bytes", released)
        return released

    def drain(self) -> List[pa.RecordBatch]:
        """All buffered batches in arrival order (spilled runs first, since
        spills always capture the oldest prefix); resets the buffer."""
        out: List[pa.RecordBatch] = []
        for s in self._spills:
            out.extend(s.read_batches())
        self._spills = []
        out.extend(self._mem)
        self._mem = []
        self._mem_bytes = 0
        self.rows = 0
        self.update_mem_used(0)
        return out


class WindowExec(ExecutionPlan):

    def __init__(self, child: ExecutionPlan,
                 funcs: Sequence[WindowFunc],
                 partition_by: Sequence[PhysicalExpr],
                 order_by: Sequence[Tuple[PhysicalExpr, bool, bool]],
                 group_limit: Optional[int] = None):
        super().__init__([child])
        self.funcs = list(funcs)
        self.partition_by = list(partition_by)
        self.order_by = list(order_by)
        self.group_limit = group_limit
        in_schema = child.schema
        for f in self.funcs:
            if isinstance(f, WindowAggFunc):
                f.agg.bind(in_schema)
        self._out_schema = Schema(
            list(in_schema) + [f.out_field(in_schema) for f in self.funcs])

    @property
    def schema(self) -> Schema:
        return self._out_schema

    def execute(self, partition: int) -> BatchIterator:
        # Stream in partition-boundary-aligned chunks: input is sorted by
        # partition_by (the planner places a SortExec below, as Spark does),
        # so once a later partition starts every earlier one is complete and
        # can be processed + emitted.  The buffer is a spill-capable
        # MemConsumer; peak working memory is the largest single partition,
        # not the whole input (ref window_exec.rs streaming processors).
        from blaze_tpu.memory import MemManager

        buf = _WindowBuffer(self)
        buf.set_spillable(MemManager.get())
        flush_rows = 4 * config.BATCH_SIZE.get()
        prev_last: Optional[tuple] = None  # prior batch's last-row part keys
        last_cut: Optional[int] = None  # buffer-relative last partition start
        try:
            for b in self.children[0].execute(partition):
                rb = b.compact().to_arrow()
                if rb.num_rows == 0:
                    continue
                if self.partition_by:
                    # incremental boundary scan: only THIS batch's keys are
                    # evaluated; the seam is detected by comparing row 0
                    # against the cached key values of the previous batch's
                    # last row (no batch copy, no buffer rescan, no spill
                    # rehydration just to look)
                    base = buf.rows
                    keys = self._part_keys(rb)
                    n = rb.num_rows
                    seg = np.zeros(n, dtype=bool)
                    for k in keys:
                        seg[1:] |= k[1:] != k[:-1]
                    if prev_last is not None:
                        seg[0] = any(k[0] != pl
                                     for k, pl in zip(keys, prev_last))
                    idx = np.flatnonzero(seg)
                    idx = idx[idx + base > 0]  # buffer row 0 is not a cut
                    if len(idx):
                        last_cut = int(idx[-1]) + base
                    prev_last = tuple(k[-1] for k in keys)
                buf.add(rb)
                if self.partition_by and buf.rows >= flush_rows \
                        and last_cut is not None:
                    whole = pa.Table.from_batches(buf.drain()) \
                        .combine_chunks().to_batches()[0]
                    # take() materializes a copy: a plain slice would pin
                    # every drained buffer while the accounting only sees
                    # the slice's logical bytes
                    tail_idx = pa.array(
                        np.arange(last_cut, whole.num_rows), type=pa.int64())
                    buf.add(whole.take(tail_idx))
                    head = whole.slice(0, last_cut)
                    last_cut = None
                    yield from self._process(head)
            tail = buf.drain()
            if tail:
                tbl = pa.Table.from_batches(tail).combine_chunks()
                if tbl.num_rows:
                    yield from self._process(tbl.to_batches()[0])
        finally:
            buf.unregister()

    # ------------------------------------------------------------------
    def _process(self, rb: pa.RecordBatch) -> List[ColumnBatch]:
        n = rb.num_rows
        in_schema = self.children[0].schema
        cb = ColumnBatch.from_arrow(rb)

        xp = _window_xp()
        part_seg, order_change = self._segments(rb, cb, xp)
        # positions & per-partition geometry (prefix scans; xp = numpy
        # on host placement, jnp on device)
        pos = xp.arange(n, dtype=xp.int64)
        seg_start = _segment_start(part_seg, pos, xp)
        row_number = (pos - seg_start + 1).astype(xp.int32)
        # partition sizes via boundary scatter
        part_size = _segment_size(part_seg, n, xp)

        # rank: position of the last (partition-or-order) change before/at row
        change = part_seg | order_change
        rank_pos = _running_max_where(change, pos, xp)
        rank_val = (rank_pos - seg_start + 1).astype(xp.int32)
        dense = _segmented_cumsum(order_change & ~part_seg, part_seg,
                                  xp).astype(xp.int32) + 1

        out_cols: List[pa.Array] = list(rb.columns)
        np_part_seg = np.asarray(part_seg)
        for f in self.funcs:
            if isinstance(f, RankFunc):
                out_cols.append(self._rank_col(f, row_number, rank_val, dense,
                                               part_size, seg_start, change,
                                               pos, n, xp))
            elif isinstance(f, LeadLagFunc):
                out_cols.append(self._lead_lag(f, cb, np_part_seg, n))
            elif isinstance(f, NthValueFunc):
                out_cols.append(self._nth_value(f, cb, seg_start, part_size, n))
            elif isinstance(f, WindowAggFunc):
                out_cols.append(self._window_agg(f, cb, rb, part_seg,
                                                 order_change, n, xp))
            else:
                raise TypeError(f"unknown window function {f}")

        out_schema = self.schema.to_arrow()
        out_cols = [a.cast(fld.type, safe=False)
                    if not a.type.equals(fld.type) else a
                    for a, fld in zip(out_cols, out_schema)]
        out = pa.RecordBatch.from_arrays(out_cols, schema=out_schema)
        if self.group_limit is not None:
            # window-group-limit: keep rows with rank <= k (proto :600)
            keep = np.asarray(rank_val) <= self.group_limit
            out = out.filter(pa.array(keep))
        return [ColumnBatch.from_arrow(out)]

    def _part_keys(self, rb: pa.RecordBatch,
                   cb: Optional[ColumnBatch] = None) -> List[np.ndarray]:
        """Order-key-encoded partition_by columns (host arrays)."""
        n = rb.num_rows
        if cb is None:
            cb = ColumnBatch.from_arrow(rb)
        arrays = [e.evaluate(cb).to_host(n) for e in self.partition_by]
        prb = pa.RecordBatch.from_arrays(
            arrays, names=[f"p{i}" for i in range(len(arrays))])
        return host_sort_keys(prb, list(range(len(arrays))),
                              [False] * len(arrays), [True] * len(arrays))

    def _part_boundaries(self, rb: pa.RecordBatch,
                         cb: Optional[ColumnBatch] = None) -> np.ndarray:
        """Bool array marking rows where a new partition starts."""
        n = rb.num_rows
        part_seg = np.zeros(n, dtype=bool)
        part_seg[0] = True
        if self.partition_by:
            for k in self._part_keys(rb, cb):
                part_seg[1:] |= k[1:] != k[:-1]
        return part_seg

    def _segments(self, rb: pa.RecordBatch, cb: ColumnBatch, xp=jnp):
        """(partition_boundary, order_change) bool arrays over rows."""
        n = rb.num_rows
        part_seg = self._part_boundaries(rb, cb)
        if self.order_by:
            arrays = [e.evaluate(cb).to_host(n) for e, _, _ in self.order_by]
            orb = pa.RecordBatch.from_arrays(
                arrays, names=[f"o{i}" for i in range(len(arrays))])
            keys = host_sort_keys(orb, list(range(len(arrays))),
                                  [d for _, d, _ in self.order_by],
                                  [f for _, _, f in self.order_by])
            order_change = np.zeros(n, dtype=bool)
            order_change[0] = True
            for k in keys:
                order_change[1:] |= k[1:] != k[:-1]
        else:
            order_change = np.ones(n, dtype=bool)
        return xp.asarray(part_seg), xp.asarray(order_change)

    def _rank_col(self, f: RankFunc, row_number, rank_val, dense, part_size,
                  seg_start, change, pos, n, xp=jnp) -> pa.Array:
        k = f.kind
        if k == WindowRankType.ROW_NUMBER:
            return pa.array(np.asarray(row_number), type=pa.int32())
        if k == WindowRankType.RANK:
            return pa.array(np.asarray(rank_val), type=pa.int32())
        if k == WindowRankType.DENSE_RANK:
            return pa.array(np.asarray(dense), type=pa.int32())
        if k == WindowRankType.PERCENT_RANK:
            denom = xp.maximum(part_size - 1, 1).astype(xp.float64)
            out = (rank_val.astype(xp.float64) - 1.0) / denom
            out = xp.where(part_size == 1, 0.0, out)
            return pa.array(np.asarray(out), type=pa.float64())
        # CUME_DIST: (last row position with same order value + 1 - start)/size
        last_same = _next_change_pos(change, pos, n, xp)
        out = (last_same - seg_start).astype(xp.float64) / \
            part_size.astype(xp.float64)
        return pa.array(np.asarray(out), type=pa.float64())

    def _lead_lag(self, f: LeadLagFunc, cb: ColumnBatch, part_seg: np.ndarray,
                  n: int) -> pa.Array:
        vals = f.expr.evaluate(cb).to_host(n)
        off = f.offset
        pid = np.cumsum(part_seg) - 1
        idx = np.arange(n) + off
        ok = (idx >= 0) & (idx < n)
        safe = np.clip(idx, 0, n - 1)
        ok &= pid[safe] == pid  # stay inside the partition
        shifted = vals.take(pa.array(safe, type=pa.int64()))
        default = pa.scalar(f.default, type=vals.type)
        return pc.if_else(pa.array(ok), shifted, default)

    def _nth_value(self, f: NthValueFunc, cb: ColumnBatch, seg_start,
                   part_size, n: int) -> pa.Array:
        vals = f.expr.evaluate(cb).to_host(n)
        starts = np.asarray(seg_start)
        if f.ignore_nulls:
            # nth NON-NULL row of the partition: rank each non-null value
            # within its partition via a prefix count, pick rank == n
            valid = np.asarray(vals.is_valid())
            cum = np.cumsum(valid)
            base = cum[starts] - valid[starts]
            rank = cum - base
            is_nth = valid & (rank == f.n)
            nth_idx = np.full(n, -1, dtype=np.int64)
            rows = np.nonzero(is_nth)[0]
            nth_idx[starts[rows]] = rows
            target = nth_idx[starts]
            ok = target >= 0
        else:
            target = starts + (f.n - 1)
            ok = (f.n - 1) < np.asarray(part_size)
        safe = np.clip(target, 0, n - 1)
        taken = vals.take(pa.array(safe, type=pa.int64()))
        return pc.if_else(pa.array(ok), taken,
                          pa.scalar(None, type=vals.type))

    def _window_agg(self, f: WindowAggFunc, cb: ColumnBatch,
                    rb: pa.RecordBatch, part_seg, order_change, n, xp=jnp
                    ) -> pa.Array:
        from blaze_tpu.ops.agg.functions import (AvgAgg, CountAgg, MinMaxAgg,
                                                 SumAgg)
        e = f.agg.children[0] if f.agg.children else None
        if e is not None:
            v = e.evaluate(cb)
            host_fast = (xp is np and
                         e.data_type(cb.schema).id != TypeId.DECIMAL)
            if host_fast:
                arr = v.to_host(n)
                data = np.asarray(arr.cast(
                    pa.float64() if pa.types.is_floating(arr.type)
                    else pa.int64(), safe=False).fill_null(0))
                valid = np.asarray(arr.is_valid())
            else:
                # decimals keep the unscaled-int64 device representation
                # on either placement (a float/int cast would truncate
                # the fraction)
                dv = v.to_device(cb.capacity)
                data = dv.data[:n]
                valid = dv.validity[:n]
                if xp is np:
                    data = np.asarray(data)
                    valid = np.asarray(valid)
        else:
            data = xp.ones(n, dtype=xp.int64)
            valid = xp.ones(n, dtype=bool)
        running = f.running and bool(self.order_by)
        if isinstance(f.agg, CountAgg):
            acc = _segmented_cumsum(valid.astype(xp.int64), part_seg, xp)
            out, ovalid = acc, xp.ones(n, dtype=bool)
        elif isinstance(f.agg, (SumAgg, AvgAgg)):
            dt = xp.float64 if xp.issubdtype(data.dtype, xp.floating) \
                else xp.int64
            s = _segmented_cumsum(xp.where(valid, data.astype(dt), 0),
                                  part_seg, xp)
            c = _segmented_cumsum(valid.astype(xp.int64), part_seg, xp)
            if isinstance(f.agg, SumAgg):
                out, ovalid = s, c > 0
            else:
                out = s.astype(xp.float64) / xp.maximum(c, 1)
                ovalid = c > 0
        elif isinstance(f.agg, MinMaxAgg):
            big = xp.iinfo(xp.int64).max if not xp.issubdtype(
                data.dtype, xp.floating) else xp.inf
            fill = big if f.agg.minimum else (-big if not xp.issubdtype(
                data.dtype, xp.floating) else -xp.inf)
            x = xp.where(valid, data, xp.asarray(fill, dtype=data.dtype))
            out = _segmented_cummin(x, part_seg, xp) if f.agg.minimum \
                else _segmented_cummax(x, part_seg, xp)
            ovalid = _segmented_cumsum(valid.astype(xp.int64), part_seg,
                                       xp) > 0
        else:
            raise TypeError(f"window agg {f.agg.name} unsupported")
        if not running:
            # whole-partition frame: broadcast the partition's last value
            last = _partition_last(out, part_seg, n, xp)
            out = last
            ovalid = _partition_last(ovalid.astype(xp.int64), part_seg, n,
                                     xp) > 0
        else:
            # RANGE frame: ties (same order value) share the frame end value
            last_same = _next_change_pos(part_seg | order_change,
                                         xp.arange(n, dtype=xp.int64),
                                         n, xp) - 1
            out = xp.take(out, last_same)
            ovalid = xp.take(ovalid, last_same)
        d = np.asarray(out)
        m = ~np.asarray(ovalid)
        return pa.array(d, mask=m)


# -- prefix-scan helpers ------------------------------------------------------
# xp-parameterized: device placement runs them as jnp (XLA fuses the scan
# chains); host placement runs numpy directly — eagerly dispatched jnp on
# the CPU backend compiles one tiny XLA program per op PER SHAPE, which
# dominated window-heavy queries (q51: ~4s of compiles for ~0.1s of work).

def _window_xp():
    from blaze_tpu.bridge.placement import host_resident
    return np if host_resident() else jnp


def _cummax(x, xp):
    if xp is np:
        return np.maximum.accumulate(x)
    import jax.lax
    return jax.lax.cummax(x)


def _cummin(x, xp):
    if xp is np:
        return np.minimum.accumulate(x)
    import jax.lax
    return jax.lax.cummin(x)


def _segment_start(part_seg, pos, xp=jnp):
    return _running_max_where(part_seg, pos, xp)


def _running_max_where(mask, pos, xp=jnp):
    """For each row, the position of the most recent row where mask=True."""
    marked = xp.where(mask, pos, xp.int64(-1))
    return _cummax(marked, xp)


def _segment_size(part_seg, n, xp=jnp):
    pos = xp.arange(n, dtype=xp.int64)
    start = _segment_start(part_seg, pos, xp)
    # size = next_start - start; next start found from the right
    is_last = xp.concatenate([part_seg[1:], xp.ones(1, dtype=bool)])
    end_pos = _next_true_pos(is_last, pos, n, xp)
    return end_pos - start + 1


def _next_true_pos(mask, pos, n, xp=jnp):
    """Position of the next row (>= current) where mask is True."""
    marked = xp.where(mask, pos, xp.int64(n))
    return xp.flip(_cummin(xp.flip(marked), xp))


def _next_change_pos(change, pos, n, xp=jnp):
    """Exclusive end of the run of rows equal to this row: position of the
    next change after current, or n."""
    nxt = xp.concatenate([change[1:], xp.ones(1, dtype=bool)])
    return _next_true_pos(nxt, pos, n, xp) + 1


def _partition_last(values, part_seg, n, xp=jnp):
    """Broadcast each partition's LAST row value to all its rows."""
    pos = xp.arange(n, dtype=xp.int64)
    is_last = xp.concatenate([part_seg[1:], xp.ones(1, dtype=bool)])
    last_pos = _next_true_pos(is_last, pos, n, xp)
    return xp.take(values, xp.clip(last_pos, 0, n - 1))


def _segmented_cumsum(values, part_seg, xp=jnp):
    """Cumulative sum restarting at each partition boundary."""
    total = xp.cumsum(values)
    pos = xp.arange(values.shape[0], dtype=xp.int64)
    start = _segment_start(part_seg, pos, xp)
    base = xp.take(total, xp.maximum(start - 1, 0))
    base = xp.where(start == 0, xp.zeros_like(base), base)
    return total - base


def _segmented_cummax(values, part_seg, xp=jnp):
    n = values.shape[0]
    pid = xp.cumsum(part_seg.astype(xp.int64)) - 1
    if xp is np:
        import pandas as pd
        # segmented running max in C; skipna=False propagates NaN like
        # the device path's jnp.maximum (NaN dominates a running max)
        return pd.Series(values).groupby(np.asarray(pid)) \
            .cummax(skipna=False).to_numpy()
    # log-steps doubling scan bounded by segment membership
    out = values
    shift = 1
    while shift < n:
        prev = xp.concatenate([out[:shift], out[:-shift]])
        prev_pid = xp.concatenate([pid[:shift], pid[:-shift]])
        ok = (xp.arange(n) >= shift) & (prev_pid == pid)
        out = xp.where(ok, xp.maximum(out, prev), out)
        shift *= 2
    return out


def _segmented_cummin(values, part_seg, xp=jnp):
    return -_segmented_cummax(-values, part_seg, xp)


# -- event-time windows (streaming runtime) ----------------------------------
# Parity: Flink's SliceAssigners / WindowOperator watermark semantics
# (the reference accelerates the operator *body*; window assignment and
# the watermark clock stay host-side, exactly as here).  The streaming
# StreamExecutor (streaming/executor.py) feeds scheduler output batches
# through EventTimeWindowState and fires panes when the watermark passes
# window end; state snapshots ride in the checkpoint manifest.


@dataclass(frozen=True)
class EventTimeWindowSpec:
    """Tumbling (slide_ms None) or sliding event-time window, epoch ms."""

    size_ms: int
    slide_ms: Optional[int] = None

    def __post_init__(self):
        if self.size_ms <= 0:
            raise ValueError("window size_ms must be > 0")
        if self.slide_ms is not None and self.slide_ms <= 0:
            raise ValueError("window slide_ms must be > 0")

    def assign(self, ts_ms: int) -> List[int]:
        """Window starts containing ts (Flink SlidingEventTimeWindows
        .assignWindows; one start for tumbling)."""
        slide = self.slide_ms or self.size_ms
        last = ts_ms - (ts_ms % slide)
        starts = []
        w = last
        while w > ts_ms - self.size_ms:
            starts.append(w)
            w -= slide
        return starts

    def end(self, start_ms: int) -> int:
        return start_ms + self.size_ms


class WatermarkTracker:
    """Event-time clock: per-partition max record timestamp, watermark =
    min over partitions that have emitted - allowed lateness (Flink's
    per-split watermark combination; never-seen partitions are idle and
    do not hold the clock back).  A record with ts >= watermark is on
    time; the watermark only moves forward."""

    def __init__(self, lateness_ms: int = 0):
        self.lateness_ms = int(lateness_ms)
        self._max_ts: dict = {}
        self._wm: Optional[int] = None

    def observe(self, partition: int, ts_ms: int) -> None:
        cur = self._max_ts.get(partition)
        if cur is None or ts_ms > cur:
            self._max_ts[partition] = int(ts_ms)

    def watermark(self) -> Optional[int]:
        if not self._max_ts:
            return self._wm
        wm = min(self._max_ts.values()) - self.lateness_ms
        if self._wm is None or wm > self._wm:
            self._wm = wm
        return self._wm

    def snapshot(self) -> dict:
        return {"max_ts": {str(p): t for p, t in self._max_ts.items()},
                "wm": self._wm}

    def restore(self, state: dict) -> None:
        self._max_ts = {int(p): int(t)
                        for p, t in (state.get("max_ts") or {}).items()}
        self._wm = state.get("wm")


_ETW_AGGS = ("count", "sum", "min", "max", "avg")


class EventTimeWindowState(MemConsumer):
    """Keyed windowed-aggregation state for the streaming runtime.

    Folds scheduler output rows into per-(window, key) accumulators;
    `advance(wm)` fires every pane whose window end <= watermark.  Late
    rows (ts < watermark at arrival) follow the late-side policy:
    `drop` counts them, `side` buffers them for `take_late()`, `accept`
    folds them into the pane's RETAINED accumulator — a fired pane
    re-opens with the state it fired with, so the re-emitted pane
    carries corrected cumulative values (valid for min/max/avg, not
    just count/sum deltas) and downstream treats it as an update.
    Accept therefore keeps fired accumulators for the life of the query
    (counted in `state_bytes()`, so memory quotas see them); drop/side
    retain nothing after a fire.  The whole
    state is JSON-snapshotable so it rides in the checkpoint manifest,
    and the object is a MemConsumer so per-query memory quotas see the
    retained bytes (there is no cheaper tier than firing: spill()
    releases nothing, so quota pressure climbs the degrade ladder)."""

    def __init__(self, spec: EventTimeWindowSpec, in_schema: pa.Schema,
                 ts_field: str, key_fields: Sequence[str],
                 aggs: Sequence[Tuple[str, Optional[str]]],
                 late_policy: str = "drop"):
        MemConsumer.__init__(self, "EventTimeWindowState")
        self.spec = spec
        self.ts_field = ts_field
        self.key_fields = list(key_fields)
        for fn, _col in aggs:
            if fn not in _ETW_AGGS:
                raise ValueError(f"unsupported window agg {fn!r}")
        self.aggs = [(fn, col) for fn, col in aggs]
        self.late_policy = late_policy
        if late_policy not in ("drop", "side", "accept"):
            raise ValueError(f"unknown late-side policy {late_policy!r}")
        self._in_schema = in_schema
        # (window_start, key tuple) -> [acc per agg]
        self._state: dict = {}
        self.late_records = 0
        self._late_rows: List[dict] = []
        # accept policy: accumulators of already-fired panes, kept so a
        # late row re-opens its pane with cumulative state
        self._fired: dict = {}
        from blaze_tpu.memory import MemManager
        self.set_spillable(MemManager.get())

    # -- accumulators ---------------------------------------------------
    @staticmethod
    def _acc_init(fn: str):
        if fn == "count":
            return 0
        if fn == "avg":
            return [0.0, 0]
        return None  # sum/min/max start empty (null on no input)

    @staticmethod
    def _acc_fold(fn: str, acc, v):
        if fn == "count":
            return acc + (1 if v is not None else 0)
        if v is None:
            return acc
        if fn == "sum":
            return v if acc is None else acc + v
        if fn == "min":
            return v if acc is None or v < acc else acc
        if fn == "max":
            return v if acc is None or v > acc else acc
        if fn == "avg":
            return [acc[0] + v, acc[1] + 1]
        raise ValueError(fn)

    @staticmethod
    def _acc_result(fn: str, acc):
        if fn == "avg":
            return acc[0] / acc[1] if acc[1] else None
        return acc

    # -- folding --------------------------------------------------------
    def add_batch(self, rb, partition: Optional[int] = None,
                  watermark: Optional[int] = None) -> int:
        """Fold one RecordBatch/Table; returns the late-record count for
        this batch (already routed per policy)."""
        cols = {name: rb.column(i).to_pylist()
                for i, name in enumerate(rb.schema.names)}
        ts_col = cols[self.ts_field]
        keys = [cols[k] for k in self.key_fields]
        vals = [cols[c] if c is not None else None for _fn, c in self.aggs]
        late = 0
        for r in range(len(ts_col)):
            ts = ts_col[r]
            key = tuple(k[r] for k in keys)
            if (watermark is not None and ts is not None
                    and ts < watermark):
                late += 1
                if self.late_policy == "drop":
                    continue
                if self.late_policy == "side":
                    self._late_rows.append(
                        {n: cols[n][r] for n in rb.schema.names})
                    continue
                # accept: fall through and fold (pane may re-fire)
            for w in self.spec.assign(int(ts)):
                slot = self._state.get((w, key))
                if slot is None:
                    # re-open a fired pane with the accumulators it
                    # fired with (accept policy), else start fresh
                    slot = self._fired.pop((w, key), None)
                    if slot is None:
                        slot = [self._acc_init(fn) for fn, _ in self.aggs]
                    self._state[(w, key)] = slot
                for i, (fn, _col) in enumerate(self.aggs):
                    # col None = count(*): every row counts
                    v = vals[i][r] if vals[i] is not None else 1
                    slot[i] = self._acc_fold(fn, slot[i], v)
        self.late_records += late
        self.update_mem_used(self.state_bytes())
        return late

    # -- firing ---------------------------------------------------------
    def _out_schema(self) -> pa.Schema:
        fields = [self._in_schema.field(k) for k in self.key_fields]
        fields += [pa.field("window_start", pa.int64()),
                   pa.field("window_end", pa.int64())]
        for i, (fn, col) in enumerate(self.aggs):
            name = f"{fn}_{col}" if col else fn
            if fn == "count":
                t = pa.int64()
            elif fn == "avg":
                t = pa.float64()
            else:
                t = self._in_schema.field(col).type
            fields.append(pa.field(name, t))
        return pa.schema(fields)

    def advance(self, watermark: Optional[int]) -> pa.Table:
        """Fire every pane whose window end <= watermark (all panes when
        watermark is None at end-of-stream flush); deterministic order
        (window_start, key)."""
        due = [wk for wk in self._state
               if watermark is None or self.spec.end(wk[0]) <= watermark]
        due.sort(key=lambda wk: (wk[0], tuple(str(k) for k in wk[1])))
        schema = self._out_schema()
        rows: List[list] = [[] for _ in schema]
        for w, key in due:
            accs = self._state.pop((w, key))
            c = 0
            for k in key:
                rows[c].append(k)
                c += 1
            rows[c].append(w)
            rows[c + 1].append(self.spec.end(w))
            c += 2
            for i, (fn, _col) in enumerate(self.aggs):
                rows[c + i].append(self._acc_result(fn, accs[i]))
            if self.late_policy == "accept":
                self._fired[(w, key)] = accs
        self.update_mem_used(self.state_bytes())
        arrays = [pa.array(v, type=f.type)
                  for v, f in zip(rows, schema)]
        return pa.Table.from_arrays(arrays, schema=schema)

    def flush(self) -> pa.Table:
        """End-of-stream: fire everything still buffered."""
        return self.advance(None)

    def take_late(self) -> List[dict]:
        out, self._late_rows = self._late_rows, []
        return out

    # -- checkpoint snapshot --------------------------------------------
    def state_bytes(self) -> int:
        # rough retained-bytes model: dict entry + key tuple + accs
        per = 96 + 24 * (len(self.key_fields) + len(self.aggs))
        return ((len(self._state) + len(self._fired)) * per
                + 48 * len(self._late_rows))

    @staticmethod
    def _panes_out(panes: dict) -> list:
        return [[w, list(key), accs]
                for (w, key), accs in
                sorted(panes.items(),
                       key=lambda kv: (kv[0][0], str(kv[0][1])))]

    def snapshot(self) -> dict:
        return {"windows": self._panes_out(self._state),
                "fired": self._panes_out(self._fired),
                "late_records": self.late_records}

    def restore(self, state: dict) -> None:
        self._state = {(int(w), tuple(key)): list(accs)
                       for w, key, accs in (state.get("windows") or [])}
        self._fired = {(int(w), tuple(key)): list(accs)
                       for w, key, accs in (state.get("fired") or [])}
        self.late_records = int(state.get("late_records", 0))
        self.update_mem_used(self.state_bytes())

    def spill(self) -> int:
        # window accumulators have no colder tier (firing early would
        # break event-time semantics); report nothing released so quota
        # arbitration escalates to the degrade ladder instead
        return 0

    def close(self) -> None:
        self.unregister()
