"""Aggregate functions over segmented (sort-based) group layouts.

Parity: the reference's Agg trait — partial_update / partial_merge /
final_merge over columnar accumulators (ref: datafusion-ext-plans/src/agg/
agg.rs:41,55,63,71; acc.rs:39 AccColumn; impls sum.rs, avg.rs, count.rs,
maxmin.rs:316, first.rs:346, first_ignores_null.rs, collect.rs:749,
bloom_filter.rs:312).

TPU-first redesign: the reference updates accumulators through a hash map of
group slots; here groups arrive as SORTED SEGMENTS (device lexsort + boundary
cumsum, SURVEY.md §7 hard-part 3), so every accumulator update is one fused
segmented reduction on device.  An agg's accumulator state is a tuple of
fixed-width device arrays indexed by dense group id ("AccTable, columnar not
row-based" — same layout philosophy as acc.rs, but jnp arrays).  Collect and
bloom keep host accumulators (variable width), mirroring the reference's
boxed AccColumn for dynamic types.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from blaze_tpu.batch import ColumnBatch
from blaze_tpu.exprs import PhysicalExpr
from blaze_tpu.kernels import sort as K
from blaze_tpu.xputil import xp_of
from blaze_tpu.schema import (BOOL, BINARY, DataType, Field, FLOAT64, INT64,
                              Schema, TypeId)

# Arrays on device per group slot; host accs are lists of python objects.
AccArrays = Tuple


class AggFunction:
    """One aggregate function instance bound to its input expressions."""

    name = "agg"

    def __init__(self, children: Sequence[PhysicalExpr]):
        self.children = list(children)
        self.input_type: Optional[DataType] = None

    def bind(self, input_schema: Schema) -> None:
        """Resolve input type once (AggExec calls this at plan time)."""
        if self.children:
            self.input_type = self.children[0].data_type(input_schema)

    # -- schema -------------------------------------------------------------
    def acc_fields(self, input_schema: Schema) -> List[Field]:
        """Accumulator columns as materialized in partial batches."""
        raise NotImplementedError

    def output_type(self, input_schema: Schema) -> DataType:
        raise NotImplementedError

    # -- device phases ------------------------------------------------------
    def partial_update(self, args: List[Tuple[jax.Array, jax.Array]],
                       gids: jax.Array, num_segments: int) -> AccArrays:
        """Raw inputs (sorted by group) -> per-group accumulator arrays.
        `args[i]` = (data, validity) gathered through the sort permutation."""
        raise NotImplementedError

    def partial_merge(self, accs: List[Tuple[jax.Array, jax.Array]],
                      gids: jax.Array, num_segments: int) -> AccArrays:
        """Partial accumulator columns (sorted by group) -> combined accs."""
        raise NotImplementedError

    def final_eval(self, accs: List[Tuple[jax.Array, jax.Array]]
                   ) -> Tuple[jax.Array, jax.Array]:
        """Combined accumulator columns -> (data, validity) result column."""
        raise NotImplementedError

    @property
    def is_host(self) -> bool:
        return False


def _out_num_type(dt: DataType) -> DataType:
    """Spark sum/avg result types: int sums stay int64, floats f64,
    decimal sums keep decimal (scale preserved, precision widened)."""
    if dt.id == TypeId.DECIMAL:
        return DataType(TypeId.DECIMAL, min(dt.precision + 10, 18), dt.scale)
    if dt.id in (TypeId.FLOAT32, TypeId.FLOAT64):
        return FLOAT64
    return INT64


class SumAgg(AggFunction):
    name = "sum"

    def acc_fields(self, s):
        t = _out_num_type(self.children[0].data_type(s))
        return [Field("sum", t)]

    def output_type(self, s):
        return _out_num_type(self.children[0].data_type(s))

    def partial_update(self, args, gids, n):
        data, valid = args[0]
        acc_dt = jnp.float64 if jnp.issubdtype(data.dtype, jnp.floating) else jnp.int64
        s = K.segment_sum(data.astype(acc_dt), gids, n, valid)
        has = K.segment_count(valid, gids, n) > 0
        return ((s, has),)

    def partial_merge(self, accs, gids, n):
        data, valid = accs[0]
        s = K.segment_sum(data, gids, n, valid)
        has = K.segment_count(valid, gids, n) > 0
        return ((s, has),)

    def final_eval(self, accs):
        return accs[0]


class CountAgg(AggFunction):
    """count(expr) / count(*) when children empty (never-null output)."""

    name = "count"

    def acc_fields(self, s):
        return [Field("count", INT64, nullable=False)]

    def output_type(self, s):
        return INT64

    def partial_update(self, args, gids, n):
        xp = xp_of(gids)
        if self.children:
            _, valid = args[0]
            c = K.segment_count(valid, gids, n)
        else:
            ones = xp.ones(gids.shape[0], dtype=bool)
            c = K.segment_count(ones, gids, n)
        return ((c, xp.ones(n, dtype=bool)),)

    def partial_merge(self, accs, gids, n):
        data, valid = accs[0]
        c = K.segment_sum(data, gids, n, valid)
        return ((c, xp_of(c).ones(c.shape[0], dtype=bool)),)

    def final_eval(self, accs):
        data, _ = accs[0]
        return data, xp_of(data).ones(data.shape[0], dtype=bool)


class AvgAgg(AggFunction):
    name = "avg"

    def acc_fields(self, s):
        t = self.children[0].data_type(s)
        if t.id == TypeId.DECIMAL:
            sum_t = _out_num_type(t)
        elif t.id in (TypeId.FLOAT32, TypeId.FLOAT64):
            sum_t = FLOAT64
        else:
            sum_t = INT64  # Spark avg(int) sums as long
        return [Field("sum", sum_t), Field("count", INT64, nullable=False)]

    def output_type(self, s):
        t = self.children[0].data_type(s)
        if t.id == TypeId.DECIMAL:
            # Spark: avg(decimal(p,s)) -> decimal(p+4, s+4) capped
            return DataType(TypeId.DECIMAL, min(t.precision + 4, 18),
                            min(t.scale + 4, 18))
        return FLOAT64

    def partial_update(self, args, gids, n):
        data, valid = args[0]
        if jnp.issubdtype(data.dtype, jnp.floating):
            s = K.segment_sum(data.astype(jnp.float64), gids, n, valid)
        else:  # int and decimal-unscaled sums stay exact in int64
            s = K.segment_sum(data.astype(jnp.int64), gids, n, valid)
        c = K.segment_count(valid, gids, n)
        return ((s, c > 0), (c, xp_of(c).ones(n, dtype=bool)))

    def partial_merge(self, accs, gids, n):
        (s_d, s_v), (c_d, c_v) = accs
        s = K.segment_sum(s_d, gids, n, s_v)
        c = K.segment_sum(c_d, gids, n, c_v)
        return ((s, c > 0), (c, xp_of(c).ones(c.shape[0], dtype=bool)))

    def final_eval(self, accs):
        (s_d, _), (c_d, _) = accs
        xp = xp_of(s_d, c_d)
        valid = c_d > 0
        denom = xp.where(valid, c_d, 1)
        if self.input_type is not None and self.input_type.id == TypeId.DECIMAL:
            # decimal(p,s) -> decimal(p+4, s+4): unscaled*10^4 / count, HALF_UP
            num = s_d * xp.int64(10_000)
            half = denom // 2
            adj = xp.where(num >= 0, num + half, num - half)
            q = xp.sign(adj) * (xp.abs(adj) // denom)
            return q, valid
        return s_d / denom.astype(xp.float64), valid


class MinMaxAgg(AggFunction):
    def __init__(self, children, minimum: bool):
        super().__init__(children)
        self.minimum = minimum
        self.name = "min" if minimum else "max"

    def acc_fields(self, s):
        return [Field(self.name, self.children[0].data_type(s))]

    def output_type(self, s):
        return self.children[0].data_type(s)

    @property
    def is_host(self) -> bool:
        # min/max over utf8/binary accumulates host-side — there is no
        # device dtype for var-width values (Spark Min/Max on strings)
        return (self.input_type is not None
                and not self.input_type.is_fixed_width)

    def host_update(self, args: List[pa.Array], gids: np.ndarray,
                    num_segments: int) -> List[pa.Array]:
        vals = args[0]
        out: List = [None] * num_segments
        for v, g in zip(vals, gids):
            if g < num_segments and v.is_valid:
                pv = v.as_py()
                cur = out[g]
                if cur is None or (pv < cur if self.minimum
                                   else pv > cur):
                    out[g] = pv
        return [pa.array(out, type=vals.type)]

    def host_merge(self, accs: List[pa.Array], gids: np.ndarray,
                   num_segments: int) -> List[pa.Array]:
        # min of mins / max of maxes: identical fold over the acc column
        return self.host_update(accs, gids, num_segments)

    def host_eval(self, accs: List[pa.Array]) -> pa.Array:
        return accs[0]

    def _reduce(self, data, valid, gids, n):
        xp = xp_of(data, valid)
        vals, nan_mask = data, None
        if self.minimum and xp.issubdtype(
                xp.asarray(data).dtype, xp.floating):
            # Spark total order puts NaN LARGEST: min skips NaN (the
            # NaN-propagating segment_min would return NaN for any
            # group containing one) — unless the group is all-NaN
            nan_mask = xp.isnan(data)
            vals = xp.where(nan_mask, xp.inf, data)
        fn = K.segment_min if self.minimum else K.segment_max
        out = fn(vals, gids, n, valid)
        has = K.segment_count(valid, gids, n) > 0
        if nan_mask is not None:
            has_real = K.segment_count(valid & ~nan_mask, gids, n) > 0
            out = xp.where(has & ~has_real, xp.nan, out)
        xp = xp_of(out, has)
        out = xp.where(has, out, xp.zeros_like(out))
        return ((out, has),)

    def partial_update(self, args, gids, n):
        return self._reduce(args[0][0], args[0][1], gids, n)

    def partial_merge(self, accs, gids, n):
        return self._reduce(accs[0][0], accs[0][1], gids, n)

    def final_eval(self, accs):
        return accs[0]


class FirstAgg(AggFunction):
    def __init__(self, children, ignores_null: bool = False):
        super().__init__(children)
        self.ignores_null = ignores_null
        self.name = "first_ignores_null" if ignores_null else "first"

    def acc_fields(self, s):
        t = self.children[0].data_type(s)
        fields = [Field("first", t)]
        if not self.ignores_null:
            # "value is null" vs "no value yet" need separate tracking
            fields.append(Field("has", BOOL, nullable=False))
        return fields

    def output_type(self, s):
        return self.children[0].data_type(s)

    def partial_update(self, args, gids, n):
        data, valid = args[0]
        if self.ignores_null:
            v, has = K.segment_first_ignores_null(data, valid, gids, n)
            return ((v, has),)
        xp = xp_of(data, valid)
        v, vvalid = K.segment_first(data, valid, gids, n)
        has_rows = K.segment_count(xp.ones_like(valid), gids, n) > 0
        return ((v, vvalid), (has_rows, xp.ones(n, dtype=bool)))

    def partial_merge(self, accs, gids, n):
        if self.ignores_null:
            data, valid = accs[0]
            v, has = K.segment_first_ignores_null(data, valid, gids, n)
            return ((v, has),)
        (data, valid), (has, _) = accs
        # first among partials that HAVE a value (has flag), not non-null
        v, _ = K.segment_first_ignores_null(
            data, has.astype(bool), gids, n)
        vv, _ = K.segment_first_ignores_null(
            valid, has.astype(bool), gids, n)
        any_has = K.segment_count(has.astype(bool), gids, n) > 0
        return ((v, vv.astype(bool) & any_has),
                (any_has, xp_of(any_has).ones(n, dtype=bool)))

    def final_eval(self, accs):
        return accs[0]


class CollectAgg(AggFunction):
    """collect_list / collect_set — host accumulators (variable width),
    ref collect.rs:749."""

    def __init__(self, children, distinct: bool):
        super().__init__(children)
        self.distinct = distinct
        self.name = "collect_set" if distinct else "collect_list"

    @property
    def is_host(self) -> bool:
        return True

    def acc_fields(self, s):
        item = self.children[0].data_type(s)
        return [Field("items", DataType(TypeId.LIST,
                                        children=(Field("item", item),)))]

    def output_type(self, s):
        item = self.children[0].data_type(s)
        return DataType(TypeId.LIST, children=(Field("item", item),))

    # host phases operate on pa arrays + numpy gids
    def host_update(self, args: List[pa.Array], gids: np.ndarray,
                    num_segments: int) -> List[pa.Array]:
        vals = args[0]
        out: List[List] = [[] for _ in range(num_segments)]
        for v, g in zip(vals, gids):
            if g < num_segments and v.is_valid:
                out[g].append(v.as_py())
        if self.distinct:
            out = [list(dict.fromkeys(x)) for x in out]
        item_t = vals.type
        return [pa.array(out, type=pa.list_(item_t))]

    def host_merge(self, accs: List[pa.Array], gids: np.ndarray,
                   num_segments: int) -> List[pa.Array]:
        lists = accs[0]
        out: List[List] = [[] for _ in range(num_segments)]
        for v, g in zip(lists, gids):
            if g < num_segments and v.is_valid:
                out[g].extend(v.as_py())
        if self.distinct:
            out = [list(dict.fromkeys(x)) for x in out]
        return [pa.array(out, type=lists.type)]

    def host_eval(self, accs: List[pa.Array]) -> pa.Array:
        return accs[0]


class CombineUniqueAgg(CollectAgg):
    """brickhouse.combine_unique (ref agg/brickhouse/combine_unique.rs):
    collect_set over the FLATTENED elements of a list-typed input —
    merges arrays across rows into one deduplicated array."""

    def __init__(self, children):
        super().__init__(children, distinct=True)
        self.name = "combine_unique"

    def acc_fields(self, s):
        return [Field("items", self.output_type(s))]

    def output_type(self, s):
        # validated here (not acc_fields) so COMPLETE/FINAL planning,
        # which only consults output_type, rejects non-array input at
        # plan time instead of crashing mid-update
        t = self.children[0].data_type(s)
        if t.id != TypeId.LIST:
            raise TypeError("combine_unique expects an array input")
        return t

    def host_update(self, args, gids, num_segments):
        lists = args[0]
        out = [[] for _ in range(num_segments)]
        for v, g in zip(lists, gids):
            if g < num_segments and v.is_valid:
                out[g].extend(e for e in v.as_py() if e is not None)
        out = [list(dict.fromkeys(x)) for x in out]
        return [pa.array(out, type=lists.type)]


class BloomFilterAgg(AggFunction):
    """bloom_filter_agg for runtime-filter joins (ref agg/bloom_filter.rs:312):
    global (ungrouped) Spark-compatible bloom built from int64 hashes."""

    name = "bloom_filter"

    def __init__(self, children, expected_items: int = 1_000_000,
                 num_bits: Optional[int] = None):
        super().__init__(children)
        from blaze_tpu.kernels import bloom
        self.num_bits = num_bits or bloom.optimal_num_bits(expected_items, 0.03)
        self.num_hashes = bloom.optimal_num_hashes(expected_items, self.num_bits)

    @property
    def is_host(self) -> bool:
        return True

    def acc_fields(self, s):
        return [Field("bloom", BINARY)]

    def output_type(self, s):
        return BINARY

    def host_update(self, args, gids, num_segments):
        from blaze_tpu.kernels.bloom import SparkBloomFilter
        vals = args[0].cast(pa.int64())
        out = []
        npg = np.asarray(gids)
        npv = np.asarray(vals.fill_null(0), dtype=np.int64)
        valid = np.asarray(vals.is_valid())
        for g in range(num_segments):
            f = SparkBloomFilter(self.num_bits, self.num_hashes)
            f.put_longs(npv[(npg == g) & valid])
            out.append(f.to_bytes())
        return [pa.array(out, type=pa.binary())]

    def host_merge(self, accs, gids, num_segments):
        from blaze_tpu.kernels.bloom import SparkBloomFilter
        out = []
        npg = np.asarray(gids)
        for g in range(num_segments):
            f: Optional[SparkBloomFilter] = None
            for i in np.nonzero(npg == g)[0]:
                v = accs[0][int(i)]
                if not v.is_valid:
                    continue
                other = SparkBloomFilter.from_bytes(v.as_py())
                if f is None:
                    f = other
                else:
                    f.merge(other)
            out.append(f.to_bytes() if f is not None else None)
        return [pa.array(out, type=pa.binary())]

    def host_eval(self, accs):
        return accs[0]


class HostUDAF(AggFunction):
    """Engine-side UDAF fallback (ref agg/spark_udaf_wrapper.rs:451 — the
    JVM round-trip with SparkUDAFMemTracker).  The host registers four
    callables; accumulator state serializes as binary per group so partial
    batches spill/shuffle like any other column."""

    def __init__(self, name: str, children,
                 init_fn, update_fn, merge_fn, eval_fn,
                 out_type: DataType = FLOAT64):
        super().__init__(children)
        self.name = name
        self._init = init_fn      # () -> state
        self._update = update_fn  # (state, *values) -> state
        self._merge = merge_fn    # (state, state) -> state
        self._eval = eval_fn      # (state) -> python value
        self._out = out_type

    @property
    def is_host(self) -> bool:
        return True

    def acc_fields(self, s):
        return [Field("state", BINARY)]

    def output_type(self, s):
        return self._out

    def _serialize(self, state) -> bytes:
        import pickle
        return pickle.dumps(state)

    def _deserialize(self, b: bytes):
        import pickle
        return pickle.loads(b)

    def host_update(self, args: List[pa.Array], gids: np.ndarray,
                    num_segments: int) -> List[pa.Array]:
        states = [self._init() for _ in range(num_segments)]
        n = len(gids)
        pyargs = [a.to_pylist() for a in args]
        for i in range(n):
            g = int(gids[i])
            if g < num_segments:
                states[g] = self._update(states[g],
                                         *(col[i] for col in pyargs))
        return [pa.array([self._serialize(s) for s in states],
                         type=pa.binary())]

    def host_merge(self, accs: List[pa.Array], gids: np.ndarray,
                   num_segments: int) -> List[pa.Array]:
        states = [None] * num_segments
        for i, g in enumerate(gids):
            g = int(g)
            if g >= num_segments:
                continue
            v = accs[0][i]
            if not v.is_valid:
                continue
            s = self._deserialize(v.as_py())
            states[g] = s if states[g] is None else self._merge(states[g], s)
        return [pa.array([self._serialize(s if s is not None
                                          else self._init())
                          for s in states], type=pa.binary())]

    def host_eval(self, accs: List[pa.Array]) -> pa.Array:
        py = []
        for v in accs[0]:
            if not v.is_valid:
                py.append(None)
            else:
                py.append(self._eval(self._deserialize(v.as_py())))
        return pa.array(py, type=self._out.to_arrow())


# -- registry (proto AggFunction enum, auron.proto:143) ----------------------

def make_agg(name: str, children: Sequence[PhysicalExpr], **kw) -> AggFunction:
    name = name.lower()
    if name == "sum":
        return SumAgg(children)
    if name == "count":
        return CountAgg(children)
    if name == "avg":
        return AvgAgg(children)
    if name == "min":
        return MinMaxAgg(children, minimum=True)
    if name == "max":
        return MinMaxAgg(children, minimum=False)
    if name == "first":
        return FirstAgg(children, ignores_null=False)
    if name == "first_ignores_null":
        return FirstAgg(children, ignores_null=True)
    if name == "collect_list":
        return CollectAgg(children, distinct=False)
    if name == "collect_set":
        return CollectAgg(children, distinct=True)
    if name == "brickhouse.collect":
        # ref agg/brickhouse/collect.rs: delegates to AggCollectSet —
        # the Hive brickhouse collect UDAF materialized as a set
        return CollectAgg(children, distinct=True)
    if name in ("combine_unique", "brickhouse.combine_unique"):
        return CombineUniqueAgg(children)
    if name == "bloom_filter":
        return BloomFilterAgg(children, **kw)
    if name == "udaf":
        from blaze_tpu import config
        from blaze_tpu.bridge.resource import get_resource
        if not config.UDAF_FALLBACK_ENABLE.get():
            raise ValueError("UDAF host fallback disabled "
                             "(auron.udafFallback.enable=false)")
        impl = get_resource(f"udaf://{kw['udaf_name']}")
        if impl is None:
            raise KeyError(f"UDAF {kw['udaf_name']!r} not registered "
                           f"(udaf://{kw['udaf_name']})")
        return HostUDAF(kw["udaf_name"], children, *impl,
                        out_type=kw.get("out_type", FLOAT64))
    raise KeyError(f"unknown aggregate function {name}")
