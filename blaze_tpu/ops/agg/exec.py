"""Hash/Sort aggregation operator via device segmented reduction.

Parity: agg_exec.rs:59 + the agg framework (agg_ctx.rs:625 AggContext with
modes Partial/PartialMerge/Final, proto auron.proto:741-750; agg_table.rs:68
AggTable = in-mem hashing/merging states + spill cursors :784; partial-agg
skipping agg_table.rs:108-122).

TPU-first redesign (SURVEY.md §7 step 5, hard-part 3): instead of an
open-addressing hash map keyed by group-row bytes (agg_hash_map.rs), groups
form by DEVICE LEXSORT over order-key-encoded grouping columns + boundary
cumsum -> dense segment ids -> fused segmented reductions.  Cross-batch
accumulation works on "partial batches" (group keys + accumulator columns,
one row per group): they buffer and periodically re-aggregate through the
same sort+segment-reduce kernel, spill as key-sorted runs under memory
pressure, and k-way merge at output with a carry group across chunk
boundaries.  String group keys dictionary-encode to dense int64 codes per
operator instance (decoded on emit, so shuffled partials carry real values).
"""

from __future__ import annotations

import enum
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from blaze_tpu import config
from blaze_tpu.batch import ColumnBatch, DeviceColumn, bucket_capacity
from blaze_tpu.exprs import PhysicalExpr
from blaze_tpu.exprs.base import ColVal
from blaze_tpu.kernels import compare
from blaze_tpu.kernels import sort as K
from blaze_tpu.memory import MemConsumer, MemManager, Spill, try_new_spill
from blaze_tpu.ops.agg.functions import AggFunction
from blaze_tpu.ops.base import BatchIterator, ExecutionPlan
from blaze_tpu.ops.sort import merge_sorted_batches
from blaze_tpu.schema import DataType, Field, INT64, Schema, TypeId
from blaze_tpu.xputil import xp_of


class AggMode(enum.Enum):
    PARTIAL = "partial"              # raw input -> acc columns
    PARTIAL_MERGE = "partial_merge"  # acc columns -> acc columns
    FINAL = "final"                  # acc columns -> final values
    COMPLETE = "complete"            # raw input -> final values (one stage)


class AggExecMode(enum.Enum):
    HASH_AGG = "hash_agg"  # accepted for plan parity; both names run the
    SORT_AGG = "sort_agg"  # segmented-sort engine (see module docstring)


class AggExec(ExecutionPlan):

    def __init__(self, child: ExecutionPlan,
                 group_exprs: Sequence[Tuple[PhysicalExpr, str]],
                 aggs: Sequence[Tuple[AggFunction, AggMode, str]],
                 exec_mode: AggExecMode = AggExecMode.HASH_AGG,
                 skip_partial_hint: bool = False):
        super().__init__([child])
        self._group_exprs = list(group_exprs)
        self._aggs = list(aggs)
        self._exec_mode = exec_mode
        # history-seeded hint (AQE seed_agg_skip via the IR's
        # supports_partial_skipping flag): prior runs measured a probe
        # ratio high enough that partial aggregation won't reduce —
        # skip the probe window and go straight to pass-through.
        # Safety still rests on _skip_eligible().
        self.skip_partial_hint = bool(skip_partial_hint)
        in_schema = child.schema
        for fn, _, _ in self._aggs:
            fn.bind(in_schema)
        self._out_schema = self._build_schema(in_schema)

    def _build_schema(self, in_schema: Schema) -> Schema:
        fields: List[Field] = []
        for e, name in self._group_exprs:
            fields.append(Field(name, e.data_type(in_schema)))
        for fn, mode, name in self._aggs:
            if mode in (AggMode.FINAL, AggMode.COMPLETE):
                fields.append(Field(name, fn.output_type(in_schema)))
            else:
                for f in fn.acc_fields(in_schema):
                    fields.append(Field(f"{name}.{f.name}", f.data_type,
                                        f.nullable))
        return Schema(fields)

    @property
    def schema(self) -> Schema:
        return self._out_schema

    def execute(self, partition: int) -> BatchIterator:
        state = _AggState(self)
        state.set_spillable(MemManager.get())
        try:
            for batch in self.children[0].execute(partition):
                yield from state.process(batch)
            yield from state.output()
        finally:
            state.unregister()


def incremental_dict_codes(arr: pa.Array, global_arr: Optional[pa.Array],
                           cap: int):
    """Dictionary-encode one batch column against an ACCUMULATED global
    dictionary (first-seen order, stable across batches).  Shared by the
    sorted agg engine (_AggState._dict_encode) and the fused dict-device
    strategy (plan/fused.py _execute_dict_device) — the incremental
    index_in / rank-among-new construction must never diverge between
    them.  Floating keys normalize (-0.0 -> 0.0, NaN -> one canonical
    bit pattern) BEFORE encoding, like Spark's NormalizeFloatingNumbers
    upstream of grouping.  Returns (codes int64 np[cap], valid np[cap],
    new_global_dict, grew)."""
    import pyarrow.compute as pc
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    if pa.types.is_floating(arr.type):
        arr = pc.add(arr, 0.0)  # -0.0 + 0.0 == +0.0
        nan = pa.scalar(float("nan"), type=arr.type)
        arr = pc.if_else(pc.is_nan(arr), nan, arr)
    enc = arr.dictionary_encode()
    if global_arr is None:
        global_arr = pa.array([], type=enc.dictionary.type)
    local = enc.dictionary.cast(global_arr.type)
    base = len(global_arr)
    if base:
        found = pc.index_in(local, value_set=global_arr)
    else:
        found = pa.nulls(len(local), type=pa.int32())
    new_mask = np.asarray(pc.is_null(found))
    grew = bool(new_mask.any())
    if grew:
        new_vals = local.filter(pa.array(new_mask))
        global_arr = pa.concat_arrays(
            [global_arr, new_vals]) if base else new_vals
    # code per local value: existing position, or base + rank-among-new
    new_rank = np.cumsum(new_mask) - 1
    found_np = np.asarray(found.fill_null(0), dtype=np.int64)
    mapping = np.where(new_mask, base + new_rank, found_np)
    idx = enc.indices
    valid = np.zeros(cap, dtype=bool)
    valid[:len(arr)] = np.asarray(idx.is_valid())
    codes = np.zeros(cap, dtype=np.int64)
    codes[:len(arr)][valid[:len(arr)]] = mapping[
        np.asarray(idx.fill_null(0), dtype=np.int64)[valid[:len(arr)]]]
    return codes, valid, global_arr, grew


class _AggState(MemConsumer):
    """Per-partition aggregation state (the AggTable analog)."""

    def __init__(self, op: AggExec):
        super().__init__("agg")
        self.op = op
        self.metrics = op.metrics
        self.in_schema = op.children[0].schema
        self.num_keys = len(op._group_exprs)
        # dictionary per string key column: an accumulated pyarrow array
        # (codes are positions).  Vectorized lookup via pc.index_in — no
        # per-distinct-value Python — and the dictionary bytes are charged
        # to the memory budget alongside the buffered partials
        # (VERDICT r2 weak #6)
        self.dict_arrays: List[Optional[pa.Array]] = []
        for e, _ in op._group_exprs:
            fixed = e.data_type(self.in_schema).is_fixed_width
            at = e.data_type(self.in_schema).to_arrow()
            self.dict_arrays.append(None if fixed else
                                    pa.array([], type=at))
        self.buffer: List[pa.RecordBatch] = []
        self.buffered_bytes = 0
        self.spills: List[Spill] = []
        self.flush_pending: List[pa.RecordBatch] = []  # skipSpill handoff
        self._output_started = False  # guards cross-thread skipSpill
        self.skipping = False
        self.rows_seen = 0
        self.groups_emitted = 0
        self.passthrough_rows = 0
        self._probe_done = False  # the cardinality probe runs ONCE
        self._internal_schema: Optional[pa.Schema] = None

    # ------------------------------------------------------------------
    # ingest
    # ------------------------------------------------------------------
    def process(self, batch: ColumnBatch) -> Iterator[pa.RecordBatch]:
        if self.skipping:
            # pass-through lane: no lexsort, no compaction, no dict
            # encode/decode round trip, no spill — raw rows leave as
            # accumulator-shaped batches, each row its own group (the
            # partial-unmerged form PartialMerge/Final already handle)
            if self.flush_pending:
                pending, self.flush_pending = self.flush_pending, []
                yield from self._emit(pending)
            n = batch.selected_count()
            if n == 0:
                return
            self.rows_seen += n
            out = self._passthrough_batch(batch)
            if out is not None:
                yield out
            return
        partial = self._aggregate_input_batch(batch)
        if partial is None:
            return
        self.rows_seen += batch.selected_count()
        self.buffer.append(partial)
        self.buffered_bytes += partial.nbytes
        self.update_mem_used(self.buffered_bytes + self._dict_bytes())
        if self.skipping:
            # update_mem_used hit memory pressure and the manager took
            # our try_release_pressure() offer mid-update: the buffer
            # already moved to flush_pending; drain it now
            if self.flush_pending:
                pending, self.flush_pending = self.flush_pending, []
                yield from self._emit(pending)
            return
        if self._should_skip_partials():
            # flush everything downstream un-merged from now on
            # (ref AGG_TRIGGER_PARTIAL_SKIPPING, agg_table.rs:108-122)
            self.skipping = True
            self.op.metrics.add("partial_skipped", 1)
            from blaze_tpu.bridge import xla_stats
            xla_stats.note_partial_agg_skip(self.rows_seen)
            flushed, self.buffer, self.buffered_bytes = self.buffer, [], 0
            self.update_mem_used(self._dict_bytes())
            yield from self._emit(flushed)
            return
        limit = config.BATCH_SIZE.get() * 4
        if sum(rb.num_rows for rb in self.buffer) >= limit * 2:
            self._combine_buffer()

    def _skip_eligible(self) -> bool:
        """Pass-through preserves semantics only for keyed all-PARTIAL
        device aggs: host accumulators (collect/bloom/UDAF/min-max over
        strings) and merge/final stages must keep hashing."""
        return (bool(self.op._aggs)
                and all(m == AggMode.PARTIAL for _, m, _ in self.op._aggs)
                and self.num_keys > 0
                and not any(fn.is_host for fn, _, _ in self.op._aggs))

    def _should_skip_partials(self) -> bool:
        if self._probe_done or not self._skip_eligible():
            return False
        # degradation rung 1 (serving quota breach): force pass-through
        # regardless of the probe — the query trades merge ratio for
        # bounded partial-agg state (kill-switch config still respected
        # via _skip_eligible only; the ladder overrides enable/minRows)
        from blaze_tpu.bridge.context import active_query
        q = getattr(self, "query", None) or active_query()
        if q is not None and getattr(q, "force_agg_passthrough", False):
            self._probe_done = True
            return True
        if getattr(self.op, "skip_partial_hint", False):
            self._probe_done = True
            return True
        if not config.PARTIAL_AGG_SKIPPING_ENABLE.get():
            return False
        if self.rows_seen < config.PARTIAL_AGG_SKIPPING_MIN_ROWS.get():
            return False
        # one-shot probe at the end of the minRows window (the reference
        # checks once when num_records crosses partial_skipping_min_rows,
        # agg_table.rs:108-122) — re-probing every batch would re-merge
        # the buffer per batch just to re-learn the same answer
        self._probe_done = True
        self._combine_buffer()
        distinct = sum(rb.num_rows for rb in self.buffer)
        ratio = distinct / max(1, self.rows_seen)
        from blaze_tpu.bridge import xla_stats
        xla_stats.note_partial_agg_probe(self.rows_seen, distinct)
        return ratio > config.PARTIAL_AGG_SKIPPING_RATIO.get()

    # ------------------------------------------------------------------
    # pass-through lane (the AGG_TRIGGER_PARTIAL_SKIPPING fast path)
    # ------------------------------------------------------------------
    def _passthrough_batch(self, batch: ColumnBatch) -> Optional[ColumnBatch]:
        """One raw input batch -> ONE accumulator-shaped output batch with
        each row its own group.  Per-row accumulators come from
        partial_update over IDENTITY group ids (acc row i depends only on
        input row i — no cross-row reduction happens), so every agg
        function's unmerged state is produced by the same code the sorted
        engine uses, and the final merge is bit-identical.  Group keys
        leave as raw values: the per-operator dictionary never grows."""
        op = self.op
        cb = batch.compact()  # no-op unless a selection mask is pending
        n = cb.num_rows
        if n == 0:
            return None
        cap = cb.capacity
        xp = cb._xp()
        sink = _ArrowSink()
        for e, _name in op._group_exprs:
            cv = e.evaluate(cb)
            if cv.is_device and cv.dictionary is None:
                sink.add_device(cv.data, cv.validity, n)
            else:
                # host (or dict-encoded utf8: emit decoded strings — raw
                # codes must never leave as key "values")
                sink.add_host(cv.to_host(n))
        gids = xp.arange(cap)
        from blaze_tpu.ops.agg.functions import CountAgg
        for fn, _mode, _name in op._aggs:
            args = []
            for c in (c.evaluate(cb) for c in fn.children):
                if not c.dtype.is_fixed_width and isinstance(fn, CountAgg):
                    # count(utf8_col): only validity feeds the kernel
                    # (same contract as _aggregate_input_batch)
                    if c.array is None:  # dict-encoded: validity is
                        av = xp.asarray(c.validity)  # already cap-sized
                        args.append((av.astype(xp.int8), av))
                        continue
                    av = np.zeros(cap, dtype=bool)
                    av[:len(c.array)] = np.asarray(c.array.is_valid())
                    av = av if xp is np else jnp.asarray(av)
                    args.append((av.astype(xp.int8), av))
                    continue
                dv = c.to_device(cap)
                args.append((dv.data, dv.validity))
            for ad, av in fn.partial_update(args, gids, cap):
                sink.add_device(ad, av, n)
        out_schema = op.schema.to_arrow()
        arrays = [_cast_output(a, f.type)
                  for a, f in zip(sink.materialize(), out_schema)]
        out = pa.RecordBatch.from_arrays(arrays, schema=out_schema)
        self.passthrough_rows += n
        self.groups_emitted += n
        self.op.metrics.add("passthrough_rows", n)
        from blaze_tpu.bridge import xla_stats
        xla_stats.note_partial_agg_rows(n)
        return ColumnBatch.from_arrow(out)

    # ------------------------------------------------------------------
    # one input batch -> one partial batch (keys + accs, one row per group)
    # ------------------------------------------------------------------
    def _aggregate_input_batch(self, batch: ColumnBatch
                               ) -> Optional[pa.RecordBatch]:
        op = self.op
        n_sel = batch.selected_count()
        if n_sel == 0:
            return None
        cap = batch.capacity
        valid_mask = batch.row_mask()

        # evaluate group keys -> device operands + code/key columns
        key_vals = [e.evaluate(batch) for e, _ in op._group_exprs]
        key_dev = self._encode_keys(key_vals, batch)

        xp = xp_of(valid_mask, *[d for d, _v in key_dev])
        # observed-lane evidence: bench's per-stage placement breakdown
        # reads these instead of trusting the session-level default
        op.metrics.add("device_lane_batches" if xp is not np
                       else "host_lane_batches", 1)
        if self.num_keys:
            operands = []
            for (data, valid), _ in zip(key_dev, range(self.num_keys)):
                b, k = compare.order_key(data, valid,
                                         _key_dtype_of(data), False, True)
                operands.append(b)
                operands.append(k)
            perm = compare.lexsort_indices(operands, valid_mask)
            sorted_ops = [xp.take(o, perm) for o in operands]
            sorted_valid = xp.take(valid_mask, perm)
            gids, ng = K.group_ids_from_sorted(sorted_ops, sorted_valid)
            num_groups = int(ng)
        else:
            perm = xp.arange(cap)
            sorted_valid = valid_mask
            gids = xp.where(valid_mask, 0, 1)
            num_groups = 1

        if num_groups == 0:
            return None

        # per-group key values
        sink = _ArrowSink()
        for (data, valid), cv in zip(key_dev, key_vals):
            sd = xp.take(data, perm)
            sv = xp.take(valid, perm) & sorted_valid
            kd, kv = K.segment_first(sd, sv, gids, num_groups)
            sink.add_device(kd, kv, num_groups)

        mode_is_raw = {AggMode.PARTIAL: True, AggMode.COMPLETE: True,
                       AggMode.PARTIAL_MERGE: False, AggMode.FINAL: False}
        # device agg inputs
        host_gids = None
        for fn, mode, name in op._aggs:
            raw = mode_is_raw[mode]
            cols = self._agg_inputs(fn, mode, batch)
            if fn.is_host:
                if host_gids is None:
                    host_gids = self._host_gids(perm, gids, batch, num_groups)
                args_host = [c.to_host(batch.num_rows) for c in cols]
                if raw:
                    accs = fn.host_update(args_host, host_gids, num_groups)
                else:
                    accs = fn.host_merge(args_host, host_gids, num_groups)
                for a in accs:
                    sink.add_host(a)
            else:
                from blaze_tpu.ops.agg.functions import CountAgg
                args = []
                for c in cols:
                    if not c.dtype.is_fixed_width and \
                            isinstance(fn, CountAgg):
                        # count(utf8_col): only the validity mask feeds
                        # the kernel — values never reach it, so don't
                        # try a device materialization.  Other var-width
                        # aggs (max(utf8)) stay on the loud-failure path
                        # rather than reducing over a validity mask.
                        if c.array is None:  # dict-encoded utf8
                            av = xp.asarray(c.validity)
                        else:
                            av = np.zeros(cap, dtype=bool)
                            av[:len(c.array)] = np.asarray(
                                c.array.is_valid())
                            av = av if xp is np else jnp.asarray(av)
                        tv = xp.take(av, perm)
                        args.append((tv.astype(xp.int8),
                                     tv & sorted_valid))
                        continue
                    dv = c.to_device(cap)
                    args.append((xp.take(dv.data, perm),
                                 xp.take(dv.validity, perm) & sorted_valid))
                if raw:
                    accs = fn.partial_update(args, gids, num_groups)
                else:
                    accs = fn.partial_merge(args, gids, num_groups)
                for ad, av in accs:
                    sink.add_device(ad, av, num_groups)
        out_arrays = sink.materialize()
        return pa.RecordBatch.from_arrays(
            out_arrays, schema=self._internal_pa_schema(out_arrays))

    def _agg_inputs(self, fn: AggFunction, mode: AggMode,
                    batch: ColumnBatch) -> List[ColVal]:
        if mode == AggMode.PARTIAL:
            return [c.evaluate(batch) for c in fn.children]
        # acc columns arrive as input columns resolved by position: the
        # planner binds acc fields as BoundReferences in fn.children
        return [c.evaluate(batch) for c in fn.children]

    def _host_gids(self, perm, gids, batch: ColumnBatch, num_groups: int
                   ) -> np.ndarray:
        """Group ids in ORIGINAL row order for host-side accumulators."""
        n = batch.num_rows
        p = np.asarray(perm)
        g = np.asarray(gids)
        out = np.full(batch.capacity, num_groups, dtype=np.int64)
        out[p] = g
        return out[:n]

    # ------------------------------------------------------------------
    # key encoding
    # ------------------------------------------------------------------
    def _encode_keys(self, key_vals: List[ColVal], batch: ColumnBatch
                     ) -> List[Tuple[jax.Array, jax.Array]]:
        out = []
        for i, cv in enumerate(key_vals):
            if self.dict_arrays[i] is None:
                dv = cv.to_device(batch.capacity)
                out.append((dv.data, dv.validity))
            else:
                arr = cv.to_host(batch.num_rows)
                codes = self._dict_encode(i, arr, batch.capacity)
                out.append(codes)
        return out

    def _dict_encode(self, i: int, arr: pa.Array, cap: int
                     ) -> Tuple[jax.Array, jax.Array]:
        codes, valid, global_arr, grew = incremental_dict_codes(
            arr, self.dict_arrays[i], cap)
        if grew:
            self.dict_arrays[i] = global_arr
            # dictionary growth counts against the budget (spill pressure
            # comes from the same MemManager the partials use)
            self.update_mem_used(self.buffered_bytes + self._dict_bytes())
        from blaze_tpu.bridge.placement import host_resident
        if host_resident():
            return codes, valid
        return jnp.asarray(codes), jnp.asarray(valid)

    def _dict_bytes(self) -> int:
        return sum(a.nbytes for a in self.dict_arrays if a is not None)

    def _decode_keys(self, rb: pa.RecordBatch) -> List[pa.Array]:
        out = []
        import pyarrow.compute as pc
        for i in range(self.num_keys):
            col = rb.column(i)
            if self.dict_arrays[i] is None:
                out.append(col)
            else:
                dec = self.dict_arrays[i]
                taken = dec.take(col.fill_null(0).cast(pa.int64()))
                decoded = pc.if_else(col.is_valid(), taken,
                                     pa.scalar(None, type=dec.type))
                f = self.op._group_exprs[i][0].data_type(self.in_schema)
                out.append(decoded.cast(f.to_arrow()))
        return out

    def _internal_pa_schema(self, arrays: List[pa.Array]) -> pa.Schema:
        if self._internal_schema is None:
            fields = []
            for i, ((e, name), a) in enumerate(
                    zip(self.op._group_exprs, arrays)):
                fields.append(pa.field(f"__k{i}", a.type))
            j = self.num_keys
            for fn, mode, name in self.op._aggs:
                for f in fn.acc_fields(self.in_schema):
                    fields.append(pa.field(f"__a{j}", arrays[j].type))
                    j += 1
            self._internal_schema = pa.schema(fields)
        return self._internal_schema

    # ------------------------------------------------------------------
    # buffer combine + spill (MemConsumer)
    # ------------------------------------------------------------------
    def _combine_buffer(self) -> None:
        if len(self.buffer) <= 1:
            return
        tbl = pa.Table.from_batches(self.buffer).combine_chunks()
        rb = tbl.to_batches()[0]
        merged = self._merge_partial_chunk(rb)
        self.buffer = [merged] if merged is not None else []
        self.buffered_bytes = merged.nbytes if merged is not None else 0
        self.update_mem_used(self.buffered_bytes + self._dict_bytes())

    def _merge_partial_chunk(self, rb: pa.RecordBatch
                             ) -> Optional[pa.RecordBatch]:
        """Re-aggregate a partial batch (rows = groups, possibly repeated)
        through sort + partial_merge.  Used for buffer combine AND the
        spill-merge output path."""
        if rb.num_rows == 0:
            return None
        cb = _internal_to_batch(rb)
        op = self.op
        cap = cb.capacity
        valid_mask = cb.row_mask()
        xp = cb._xp()
        if self.num_keys:
            operands = []
            for i in range(self.num_keys):
                col = cb.columns[i]
                b, k = compare.order_key(col.data, col.validity, col.dtype,
                                         False, True)
                operands.extend([b, k])
            perm = compare.lexsort_indices(operands, valid_mask)
            sorted_ops = [xp.take(o, perm) for o in operands]
            sorted_valid = xp.take(valid_mask, perm)
            gids, ng = K.group_ids_from_sorted(sorted_ops, sorted_valid)
            num_groups = int(ng)
        else:
            perm = xp.arange(cap)
            sorted_valid = valid_mask
            gids = xp.where(valid_mask, 0, 1)
            num_groups = 1
        if num_groups == 0:
            return None
        sink = _ArrowSink()
        for i in range(self.num_keys):
            col = cb.columns[i]
            sd = xp.take(col.data, perm)
            sv = xp.take(col.validity, perm) & sorted_valid
            kd, kv = K.segment_first(sd, sv, gids, num_groups)
            sink.add_device(kd, kv, num_groups)
        j = self.num_keys
        host_gids = None
        for fn, mode, name in op._aggs:
            nacc = len(fn.acc_fields(self.in_schema))
            if fn.is_host:
                if host_gids is None:
                    p = np.asarray(perm)
                    g = np.asarray(gids)
                    hg = np.full(cap, num_groups, dtype=np.int64)
                    hg[p] = g
                    host_gids = hg[:rb.num_rows]
                args = [rb.column(j + t) for t in range(nacc)]
                for a in fn.host_merge(args, host_gids, num_groups):
                    sink.add_host(a)
            else:
                args = []
                for t in range(nacc):
                    col = cb.columns[j + t]
                    args.append((xp.take(col.data, perm),
                                 xp.take(col.validity, perm) & sorted_valid))
                accs = fn.partial_merge(args, gids, num_groups)
                for ad, av in accs:
                    sink.add_device(ad, av, num_groups)
            j += nacc
        return pa.RecordBatch.from_arrays(sink.materialize(),
                                          schema=self._internal_schema)

    def try_release_pressure(self) -> int:
        # a query on the degradation ladder accepts the pass-through
        # offer even with onSpill off: its quota breach already chose
        # degradation over spill IO
        q = getattr(self, "query", None)
        degraded = q is not None and getattr(q, "force_agg_passthrough",
                                             False)
        if not ((config.PARTIAL_AGG_SKIPPING_ON_SPILL.get() or degraded) and
                not self.skipping and not self._output_started and
                self.buffer and self._skip_eligible()):
            return 0
        # under pressure, hand the buffered partials downstream un-merged
        # and switch to pass-through instead of paying spill IO the final
        # stage must re-read anyway: process()/output() drain
        # flush_pending at the next pull
        # (ref auron.partialAggSkipping.skipSpill)
        self.skipping = True
        self._probe_done = True
        self.flush_pending.extend(self.buffer)
        released = self.buffered_bytes
        self.buffer = []
        self.buffered_bytes = 0
        self._mem_used = self._dict_bytes()  # dict cannot spill
        self.op.metrics.add("partial_skipped", 1)
        from blaze_tpu.bridge import xla_stats
        xla_stats.note_partial_agg_skip(self.rows_seen, on_spill=True)
        return released

    def spill(self) -> int:
        if not self.buffer:
            return 0
        released = self.try_release_pressure()
        if released:
            return released
        self._combine_buffer()
        if not self.buffer:
            return 0
        run = self.buffer[0]
        # combine sorts groups by key order already (lexsort output order)
        spill = try_new_spill()
        bs = config.BATCH_SIZE.get()
        spill.write_batches(run.slice(i, min(bs, run.num_rows - i))
                            for i in range(0, run.num_rows, bs))
        self.spills.append(spill)
        released = self.buffered_bytes
        self.buffer = []
        self.buffered_bytes = 0
        self._mem_used = self._dict_bytes()  # dict cannot spill
        self.op.metrics.add("spill_count")
        self.op.metrics.add("spilled_bytes", released)
        return released

    # ------------------------------------------------------------------
    # output
    # ------------------------------------------------------------------
    def output(self) -> Iterator[pa.RecordBatch]:
        op = self.op
        # a cross-thread skipSpill after this point would strand rows in
        # flush_pending; from here on spill() takes the normal path
        self._output_started = True
        if self.flush_pending:
            pending, self.flush_pending = self.flush_pending, []
            yield from self._emit(pending)
        self._combine_buffer()
        if not self.spills:
            batches = self.buffer
            if not batches and not self.num_keys and not self.skipping:
                empty = self._empty_global_accs()
                if empty is not None:
                    batches = [empty]
            yield from self._emit(batches)
            return
        # merge key-sorted spilled runs + in-mem run, re-merging the carry
        # group across chunk boundaries (the spill-cursor merge analog,
        # agg_table.rs:784)
        runs: List[Iterator[pa.RecordBatch]] = [s.read_batches()
                                                for s in self.spills]
        if self.buffer:
            runs.append(iter(self.buffer))
        key_cols = list(range(self.num_keys))
        merged_stream = merge_sorted_batches(
            runs, key_cols, [False] * self.num_keys, [True] * self.num_keys)
        carry: Optional[pa.RecordBatch] = None
        for chunk in merged_stream:
            if carry is not None:
                chunk = pa.Table.from_batches([carry, chunk]) \
                    .combine_chunks().to_batches()[0]
            merged = self._merge_partial_chunk(chunk)
            if merged is None:
                continue
            if merged.num_rows > 1:
                emit, carry = merged.slice(0, merged.num_rows - 1), \
                    merged.slice(merged.num_rows - 1)
                yield from self._emit([emit])
            else:
                carry = merged
        if carry is not None:
            yield from self._emit([carry])
        for s in self.spills:
            s.release()
        self.spills = []

    def _empty_global_accs(self) -> Optional[pa.RecordBatch]:
        """Global agg over empty input still emits one row (count=0 etc.)."""
        op = self.op
        out_arrays: List[pa.Array] = []
        gids = jnp.zeros(1, dtype=jnp.int32)
        for fn, mode, name in op._aggs:
            if fn.is_host:
                accs = fn.host_update(
                    [pa.nulls(1, f.data_type.to_arrow())
                     for f in [Field("x", INT64)] * max(1, len(fn.children))],
                    np.array([1]), 1)
                out_arrays.extend(accs)
            else:
                args = []
                for c in fn.children or [None]:
                    dt = (c.data_type(self.in_schema).jnp_dtype()
                          if c is not None else jnp.int64)
                    args.append((jnp.zeros(1, dtype=dt),
                                 jnp.zeros(1, dtype=bool)))
                accs = fn.partial_update(args, jnp.ones(1, dtype=jnp.int32), 1)
                for ad, av in accs:
                    out_arrays.append(_device_to_arrow(ad, av, 1))
        if not out_arrays:
            return None
        return pa.RecordBatch.from_arrays(
            out_arrays, schema=self._internal_pa_schema(out_arrays))

    def _emit(self, batches: List[pa.RecordBatch]) -> Iterator[pa.RecordBatch]:
        """Internal partial batches -> output schema (decode keys; final_eval
        when FINAL mode)."""
        op = self.op
        out_schema = op.schema.to_arrow()
        for rb in batches:
            if rb.num_rows == 0:
                continue
            sink = _ArrowSink()
            for a in self._decode_keys(rb):
                sink.add_host(a)
            j = self.num_keys
            for fn, mode, name in op._aggs:
                nacc = len(fn.acc_fields(self.in_schema))
                if mode in (AggMode.FINAL, AggMode.COMPLETE):
                    if fn.is_host:
                        sink.add_host(fn.host_eval(
                            [rb.column(j + t) for t in range(nacc)]))
                    else:
                        cap = bucket_capacity(rb.num_rows)
                        accs = []
                        for t in range(nacc):
                            f = fn.acc_fields(self.in_schema)[t]
                            dc = DeviceColumn.from_arrow(
                                rb.column(j + t), f.data_type, cap)
                            accs.append((dc.data[:rb.num_rows],
                                         dc.validity[:rb.num_rows]))
                        vd, vv = fn.final_eval(accs)
                        sink.add_device(vd, vv, rb.num_rows)
                else:
                    for t in range(nacc):
                        sink.add_host(rb.column(j + t))
                j += nacc
            arrays = sink.materialize()
            arrays = [_cast_output(a, f.type) for a, f in
                      zip(arrays, out_schema)]
            out = pa.RecordBatch.from_arrays(arrays, schema=out_schema)
            self.groups_emitted += out.num_rows
            yield ColumnBatch.from_arrow(out)


# ---------------------------------------------------------------------------

def _key_dtype_of(data: jax.Array) -> DataType:
    from blaze_tpu import schema as S
    m = {"bool": S.BOOL, "int8": S.INT8, "int16": S.INT16, "int32": S.INT32,
         "int64": S.INT64, "float32": S.FLOAT32, "float64": S.FLOAT64}
    return m[jnp.dtype(data.dtype).name]


def _device_to_arrow(data: jax.Array, valid: jax.Array, n: int) -> pa.Array:
    d = np.asarray(data)[:n]
    v = np.asarray(valid)[:n]
    if d.dtype == np.bool_:
        return pa.array(d, mask=~v)
    return pa.array(d, mask=~v)


class _ArrowSink:
    """Collects output columns, deferring device arrays so ALL of them come
    back in ONE batched device_get — per-column syncs each cost a full
    round trip on a tunneled device."""

    def __init__(self):
        self._items: List = []  # pa.Array | ("dev", data, valid, n)

    def add_host(self, arr: pa.Array) -> None:
        self._items.append(arr)

    def add_device(self, data: jax.Array, valid: jax.Array, n: int) -> None:
        self._items.append(("dev", data, valid, n))

    def materialize(self) -> List[pa.Array]:
        pending = [(it[1], it[2]) for it in self._items
                   if isinstance(it, tuple)]
        if pending and all(isinstance(d, np.ndarray) and
                           isinstance(v, np.ndarray) for d, v in pending):
            fetched = pending  # host-resident: no sync needed
        else:
            fetched = jax.device_get(pending) if pending else []
        out: List[pa.Array] = []
        j = 0
        for it in self._items:
            if isinstance(it, tuple):
                d, v = fetched[j]
                j += 1
                n = it[3]
                out.append(pa.array(d[:n], mask=~v[:n]))
            else:
                out.append(it)
        return out


def _internal_to_batch(rb: pa.RecordBatch) -> ColumnBatch:
    """Internal partial batch -> ColumnBatch with device fixed columns."""
    return ColumnBatch.from_arrow(rb)


def _cast_output(a: pa.Array, t: pa.DataType) -> pa.Array:
    if a.type.equals(t):
        return a
    if pa.types.is_decimal(t) and pa.types.is_integer(a.type):
        # internal unscaled int64 -> decimal: reinterpret at the target
        # scale, NOT an arrow value cast (which would rescale)
        import decimal as pydec
        scale = t.scale
        py = [None if not x.is_valid
              else pydec.Decimal(x.as_py()).scaleb(-scale) for x in a]
        return pa.array(py, type=t)
    return a.cast(t, safe=False)
