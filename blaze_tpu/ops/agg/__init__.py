"""Aggregation framework (ref: datafusion-ext-plans/src/agg/)."""

from blaze_tpu.ops.agg.exec import AggExec, AggExecMode, AggMode
from blaze_tpu.ops.agg.functions import (AggFunction, AvgAgg, BloomFilterAgg,
                                         CollectAgg, CountAgg, FirstAgg,
                                         MinMaxAgg, SumAgg, make_agg)

__all__ = ["AggExec", "AggExecMode", "AggMode", "AggFunction", "AvgAgg",
           "BloomFilterAgg", "CollectAgg", "CountAgg", "FirstAgg",
           "MinMaxAgg", "SumAgg", "make_agg"]
