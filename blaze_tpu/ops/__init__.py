"""Execution operators (ref: datafusion-ext-plans/src/)."""

from blaze_tpu.ops.base import (BatchIterator, CoalesceStream, ExecutionPlan,
                                coalesce)
from blaze_tpu.ops.basic import (DebugExec, EmptyPartitionsExec, ExpandExec,
                                 FilterExec, FilterProjectExec, LimitExec,
                                 ProjectExec, RenameColumnsExec, UnionExec)
from blaze_tpu.ops.scan import MemoryScanExec, ParquetScanExec
from blaze_tpu.ops.sort import SortExec
from blaze_tpu.ops.agg import AggExec, AggMode, make_agg
from blaze_tpu.ops.window import (LeadLagFunc, NthValueFunc, RankFunc,
                                  WindowAggFunc, WindowExec, WindowRankType)
from blaze_tpu.ops.generate import (ExplodeGenerator, GenerateExec,
                                    JsonTupleGenerator, UDTFGenerator)
from blaze_tpu.ops.joins import (BroadcastJoinExec, JoinType,
                                 ShuffledHashJoinExec, SortMergeJoinExec)

__all__ = [
    "BatchIterator", "CoalesceStream", "ExecutionPlan", "coalesce",
    "DebugExec", "EmptyPartitionsExec", "ExpandExec", "FilterExec",
    "FilterProjectExec", "LimitExec", "ProjectExec", "RenameColumnsExec",
    "UnionExec", "MemoryScanExec", "ParquetScanExec", "SortExec",
    "AggExec", "AggMode", "make_agg",
    "BroadcastJoinExec", "JoinType", "ShuffledHashJoinExec",
    "SortMergeJoinExec",
    "LeadLagFunc", "NthValueFunc", "RankFunc", "WindowAggFunc", "WindowExec",
    "WindowRankType", "ExplodeGenerator", "GenerateExec",
    "JsonTupleGenerator", "UDTFGenerator",
]
