"""Execution operators (ref: datafusion-ext-plans/src/)."""

from blaze_tpu.ops.base import (BatchIterator, CoalesceStream, ExecutionPlan,
                                coalesce)
from blaze_tpu.ops.basic import (DebugExec, EmptyPartitionsExec, ExpandExec,
                                 FilterExec, FilterProjectExec, LimitExec,
                                 ProjectExec, RenameColumnsExec, UnionExec)
from blaze_tpu.ops.scan import MemoryScanExec, ParquetScanExec
from blaze_tpu.ops.sort import SortExec

__all__ = [
    "BatchIterator", "CoalesceStream", "ExecutionPlan", "coalesce",
    "DebugExec", "EmptyPartitionsExec", "ExpandExec", "FilterExec",
    "FilterProjectExec", "LimitExec", "ProjectExec", "RenameColumnsExec",
    "UnionExec", "MemoryScanExec", "ParquetScanExec", "SortExec",
]
