"""Filter, Project, Limit, Union, RenameColumns, Expand, Empty, Debug.

Parity: filter_exec.rs / project_exec.rs (both through the shared
CachedExprsEvaluator, ref common/cached_exprs_evaluator.rs:522),
limit_exec.rs:305, union_exec.rs (per-input partition routing, proto
auron.proto:552-562), rename_columns_exec.rs, expand_exec.rs:506
(grouping-sets fan-out), empty_partitions_exec.rs, debug_exec.rs.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

from blaze_tpu.batch import ColumnBatch
from blaze_tpu.exprs import (CachedExprsEvaluator, FusedExprsEvaluator,
                             PhysicalExpr)
from blaze_tpu.ops.base import BatchIterator, CoalesceStream, ExecutionPlan
from blaze_tpu.schema import Field, Schema


class FilterExec(ExecutionPlan):
    """Selection-mask filter; no compaction until density drops
    (ref filter_exec.rs; compaction by CoalesceStream)."""

    def __init__(self, child: ExecutionPlan, predicates: Sequence[PhysicalExpr]):
        super().__init__([child])
        self._predicates = list(predicates)

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self, partition: int) -> BatchIterator:
        # per-partition instance, but the compiled program behind it is
        # resolved from the process-wide fingerprint cache (exprs/program)
        ev = FusedExprsEvaluator(filters=self._predicates,
                                 in_schema=self.schema)
        def gen():
            for batch in self.children[0].execute(partition):
                yield ev.filter(batch)
        return iter(CoalesceStream(gen(), metrics=self.metrics))


class ProjectExec(ExecutionPlan):
    def __init__(self, child: ExecutionPlan,
                 exprs: Sequence[PhysicalExpr], names: Sequence[str]):
        super().__init__([child])
        self._exprs = list(exprs)
        self._names = list(names)
        self._out_schema: Optional[Schema] = None

    @property
    def schema(self) -> Schema:
        if self._out_schema is None:
            in_schema = self.children[0].schema
            self._out_schema = Schema([
                Field(n, e.data_type(in_schema)) for n, e in
                zip(self._names, self._exprs)])
        return self._out_schema

    def execute(self, partition: int) -> BatchIterator:
        ev = FusedExprsEvaluator(projections=self._exprs,
                                 in_schema=self.children[0].schema)
        out_schema = self.schema
        for batch in self.children[0].execute(partition):
            yield ev.project(batch, out_schema)


class FilterProjectExec(ExecutionPlan):
    """Fused filter+project sharing one evaluator (the reference fuses these
    through the shared CachedExprsEvaluator when adjacent)."""

    def __init__(self, child: ExecutionPlan, predicates: Sequence[PhysicalExpr],
                 exprs: Sequence[PhysicalExpr], names: Sequence[str]):
        super().__init__([child])
        self._predicates = list(predicates)
        self._exprs = list(exprs)
        self._names = list(names)
        self._out_schema: Optional[Schema] = None

    @property
    def schema(self) -> Schema:
        if self._out_schema is None:
            in_schema = self.children[0].schema
            self._out_schema = Schema([
                Field(n, e.data_type(in_schema)) for n, e in
                zip(self._names, self._exprs)])
        return self._out_schema

    def execute(self, partition: int) -> BatchIterator:
        ev = FusedExprsEvaluator(filters=self._predicates,
                                 projections=self._exprs,
                                 in_schema=self.children[0].schema)
        out_schema = self.schema
        def gen():
            for batch in self.children[0].execute(partition):
                yield ev.filter_project(batch, out_schema)
        return iter(CoalesceStream(gen(), metrics=self.metrics))


class LimitExec(ExecutionPlan):
    """LocalLimit (per partition) / GlobalLimit on partition 0, with
    offset-skip (ref limit_exec.rs:305, LimitExecNode offset field)."""

    def __init__(self, child: ExecutionPlan, limit: int, offset: int = 0):
        super().__init__([child])
        self._limit = limit
        self._offset = offset

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self, partition: int) -> BatchIterator:
        to_skip = self._offset
        remaining = self._limit
        for batch in self.children[0].execute(partition):
            if remaining <= 0:
                break
            n = batch.selected_count()
            if to_skip:
                if n <= to_skip:
                    to_skip -= n
                    continue
                batch = batch.compact().take(list(range(to_skip, n)))
                n -= to_skip
                to_skip = 0
            if n <= remaining:
                remaining -= n
                yield batch
            else:
                packed = batch.compact().take(list(range(remaining)))
                remaining = 0
                yield packed
                break


class UnionExec(ExecutionPlan):
    """Concatenates children partition-wise (ref union_exec.rs; proto
    union inputs carry num_partitions/cur_partition, auron.proto:552-562)."""

    def __init__(self, children: Sequence[ExecutionPlan]):
        super().__init__(children)

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    @property
    def num_partitions(self) -> int:
        return max(c.num_partitions for c in self.children)

    def execute(self, partition: int) -> BatchIterator:
        for child in self.children:
            if partition < child.num_partitions:
                yield from child.execute(partition)


class RenameColumnsExec(ExecutionPlan):
    """Schema aliasing between stages (ref rename_columns_exec.rs)."""

    def __init__(self, child: ExecutionPlan, names: Sequence[str]):
        super().__init__([child])
        self._names = list(names)

    @property
    def schema(self) -> Schema:
        child_schema = self.children[0].schema
        return Schema([Field(n, f.data_type, f.nullable)
                       for n, f in zip(self._names, child_schema)])

    def execute(self, partition: int) -> BatchIterator:
        out_schema = self.schema
        for batch in self.children[0].execute(partition):
            yield ColumnBatch(out_schema, batch.columns, batch.num_rows,
                              batch.selection)


class ExpandExec(ExecutionPlan):
    """Grouping-sets fan-out: each input row is projected through K
    projection lists (ref expand_exec.rs:506)."""

    def __init__(self, child: ExecutionPlan,
                 projections: Sequence[Sequence[PhysicalExpr]],
                 names: Sequence[str]):
        super().__init__([child])
        self._projections = [list(p) for p in projections]
        self._names = list(names)
        self._out_schema: Optional[Schema] = None

    @property
    def schema(self) -> Schema:
        if self._out_schema is None:
            in_schema = self.children[0].schema
            self._out_schema = Schema([
                Field(n, e.data_type(in_schema)) for n, e in
                zip(self._names, self._projections[0])])
        return self._out_schema

    def execute(self, partition: int) -> BatchIterator:
        out_schema = self.schema
        evs = [CachedExprsEvaluator(projections=p) for p in self._projections]
        def gen():
            for batch in self.children[0].execute(partition):
                for ev in evs:
                    yield ev.project(batch, out_schema)
        return iter(CoalesceStream(gen(), metrics=self.metrics))


class EmptyPartitionsExec(ExecutionPlan):
    """N empty partitions (ref empty_partitions_exec.rs)."""

    def __init__(self, schema: Schema, num_partitions: int = 1):
        super().__init__()
        self._schema = schema
        self._n = num_partitions

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def num_partitions(self) -> int:
        return self._n

    def execute(self, partition: int) -> BatchIterator:
        return iter(())


class DebugExec(ExecutionPlan):
    """Pass-through that logs batches (ref debug_exec.rs)."""

    def __init__(self, child: ExecutionPlan, tag: str = "debug"):
        super().__init__([child])
        self._tag = tag

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self, partition: int) -> BatchIterator:
        import logging
        log = logging.getLogger("blaze_tpu.debug")
        for i, batch in enumerate(self.children[0].execute(partition)):
            log.info("[%s] partition=%d batch=%d rows=%d", self._tag,
                     partition, i, batch.selected_count())
            yield batch
