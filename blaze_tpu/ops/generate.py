"""Generate operator: explode / posexplode / json_tuple / UDTF.

Parity: generate_exec.rs:550 + generate/{explode,json_tuple,
spark_udtf_wrapper}.rs.  Fan-out sizes are data-dependent, so row
multiplication happens host-side with vectorized numpy repeat over Arrow
list offsets; the generated batch re-enters the device pipeline as a normal
ColumnBatch.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence

import numpy as np
import pyarrow as pa

from blaze_tpu.batch import ColumnBatch
from blaze_tpu.exprs import PhysicalExpr
from blaze_tpu.ops.base import BatchIterator, CoalesceStream, ExecutionPlan
from blaze_tpu.schema import DataType, Field, INT32, Schema, TypeId, UTF8


class Generator:
    """Produces (repeat_counts, generated_columns) for one input batch."""

    def out_fields(self, in_schema: Schema) -> List[Field]:
        raise NotImplementedError

    def generate(self, batch: ColumnBatch) -> tuple:
        raise NotImplementedError


@dataclass
class ExplodeGenerator(Generator):
    """explode/posexplode over list or map columns (ref generate/explode.rs)."""

    child: PhysicalExpr
    position: bool = False   # posexplode
    outer: bool = False      # explode_outer keeps empty/null rows

    def out_fields(self, in_schema: Schema) -> List[Field]:
        t = self.child.data_type(in_schema)
        fields = []
        if self.position:
            fields.append(Field("pos", INT32, False))
        if t.id == TypeId.LIST:
            fields.append(Field("col", t.children[0].data_type))
        elif t.id == TypeId.MAP:
            fields.append(Field("key", t.children[0].data_type))
            fields.append(Field("value", t.children[1].data_type))
        else:
            raise TypeError(f"explode over non-list/map {t}")
        return fields

    def generate(self, batch: ColumnBatch):
        n = batch.num_rows
        arr = self.child.evaluate(batch).to_host(n)
        is_map = pa.types.is_map(arr.type)
        lengths = np.asarray(pc_list_len(arr))
        if self.outer:
            counts = np.where(lengths <= 0, 1, lengths)
            empty = lengths <= 0
        else:
            counts = np.where(lengths < 0, 0, lengths)
            empty = np.zeros(n, dtype=bool)
        if is_map:
            flat = arr.values  # entries struct array (key, value)
            keys, vals = flat.field(0), flat.field(1)
        else:
            flat = arr.flatten()  # values of all lists concatenated
        # positions within each row
        total = int(counts.sum())
        pos = np.arange(total, dtype=np.int64) - \
            np.repeat(np.cumsum(counts) - counts, counts)
        # source index into the flattened values; outer-empty rows get null
        starts = np.zeros(n, dtype=np.int64)
        starts[1:] = np.cumsum(np.where(lengths < 0, 0, lengths))[:-1]
        src = np.repeat(starts, counts) + pos
        null_out = np.repeat(empty, counts)
        src_safe = np.clip(src, 0, max(len(flat) - 1, 0))
        cols: List[pa.Array] = []
        if self.position:
            p = np.where(null_out, 0, pos).astype(np.int32)
            cols.append(pa.array(p, mask=null_out, type=pa.int32()))
        idx = pa.array(src_safe, type=pa.int64())
        if is_map:
            for part in (keys, vals):
                taken = (part.take(idx) if len(part) else
                         pa.nulls(total, part.type))
                cols.append(_mask_nulls(taken, null_out))
        else:
            taken = (flat.take(idx) if len(flat) else
                     pa.nulls(total, flat.type))
            cols.append(_mask_nulls(taken, null_out))
        return counts, cols


def pc_list_len(arr: pa.Array) -> pa.Array:
    import pyarrow.compute as pc
    if pa.types.is_map(arr.type):
        # map arrays share the list offset layout; measure via offsets
        offsets = np.frombuffer(arr.buffers()[1], dtype=np.int32)[
            arr.offset:arr.offset + len(arr) + 1]
        lengths = np.diff(offsets).astype(np.int64)
        valid = (np.ones(len(arr), dtype=bool) if arr.null_count == 0
                 else np.asarray(arr.is_valid()))
        return pa.array(np.where(valid, lengths, -1))
    return pc.list_value_length(arr).fill_null(-1)


def _mask_nulls(arr: pa.Array, mask: np.ndarray) -> pa.Array:
    if not mask.any():
        return arr
    import pyarrow.compute as pc
    return pc.if_else(pa.array(~mask), arr, pa.nulls(len(arr), arr.type))


@dataclass
class JsonTupleGenerator(Generator):
    """json_tuple(json, f1, f2, ...) — one output row per input row
    (ref generate/json_tuple.rs)."""

    child: PhysicalExpr
    fields: Sequence[str] = ()

    def out_fields(self, in_schema: Schema) -> List[Field]:
        return [Field(f"c{i}", UTF8) for i in range(len(self.fields))]

    def generate(self, batch: ColumnBatch):
        n = batch.num_rows
        arr = self.child.evaluate(batch).to_host(n)
        outs: List[List[Optional[str]]] = [[] for _ in self.fields]
        for x in arr:
            doc = None
            if x.is_valid:
                try:
                    doc = json.loads(x.as_py())
                except (ValueError, TypeError):
                    doc = None
            for i, f in enumerate(self.fields):
                v = None
                if isinstance(doc, dict) and f in doc:
                    raw = doc[f]
                    v = (json.dumps(raw) if isinstance(raw, (dict, list))
                         else None if raw is None else str(raw))
                outs[i].append(v)
        counts = np.ones(n, dtype=np.int64)
        return counts, [pa.array(o, type=pa.utf8()) for o in outs]


@dataclass
class UDTFGenerator(Generator):
    """Host-callable UDTF fallback (ref generate/spark_udtf_wrapper.rs —
    the JVM round-trip analog: rows out per row in)."""

    args: Sequence[PhysicalExpr] = ()
    fn: Callable = None      # row_values -> list of output tuples
    fields: Sequence[Field] = ()

    def out_fields(self, in_schema: Schema) -> List[Field]:
        return list(self.fields)

    def generate(self, batch: ColumnBatch):
        n = batch.num_rows
        arrays = [a.evaluate(batch).to_host(n) for a in self.args]
        counts = np.zeros(n, dtype=np.int64)
        cols: List[List] = [[] for _ in self.fields]
        for i in range(n):
            row = tuple(a[i].as_py() for a in arrays)
            out_rows = self.fn(*row) or []
            counts[i] = len(out_rows)
            for tup in out_rows:
                for j, v in enumerate(tup):
                    cols[j].append(v)
        arrays_out = [pa.array(c, type=f.data_type.to_arrow())
                      for c, f in zip(cols, self.fields)]
        return counts, arrays_out


class GenerateExec(ExecutionPlan):

    def __init__(self, child: ExecutionPlan, generator: Generator,
                 required_cols: Optional[Sequence[int]] = None,
                 outer: bool = False):
        super().__init__([child])
        self.generator = generator
        self._required = (list(required_cols) if required_cols is not None
                          else list(range(len(child.schema))))
        in_schema = child.schema
        kept = [in_schema[i] for i in self._required]
        self._out_schema = Schema(kept + generator.out_fields(in_schema))

    @property
    def schema(self) -> Schema:
        return self._out_schema

    def execute(self, partition: int) -> BatchIterator:
        def gen():
            for batch in self.children[0].execute(partition):
                batch = batch.compact()
                if batch.num_rows == 0:
                    continue
                counts, gen_cols = self.generator.generate(batch)
                rb = batch.to_arrow()
                idx = pa.array(np.repeat(np.arange(batch.num_rows), counts),
                               type=pa.int64())
                kept = [rb.column(i).take(idx) for i in self._required]
                arrays = kept + list(gen_cols)
                out_schema = self.schema.to_arrow()
                arrays = [a.cast(f.type, safe=False)
                          if not a.type.equals(f.type) else a
                          for a, f in zip(arrays, out_schema)]
                out = pa.RecordBatch.from_arrays(arrays, schema=out_schema)
                yield ColumnBatch.from_arrow(out)
        return iter(CoalesceStream(gen(), metrics=self.metrics))
