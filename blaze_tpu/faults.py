"""Deterministic fault injection + the fault-tolerance exception taxonomy.

Production pillar (PAPER.md; Flare arXiv:1703.08219 makes the same
point): a native engine only displaces the reference engine if it keeps
the host's fault-tolerance contract — tasks die, disks flip bits,
shuffle fetches fail, and the query must still finish with the same
rows.  This module is the *test* side of that contract: a process-wide
injection registry with named sites threaded through the scheduler,
task pool, shuffle writer/reader and memory manager, so chaos runs
(`bench.py --chaos`, tests/test_fault_tolerance.py) can script failures
deterministically and assert bit-identical recovery.

Sites (the code points that call in here):
    task-start     bridge/tasks.py, before each task attempt
    shuffle-write  shuffle/ipc.py, per flushed frame (supports `corrupt`)
    shuffle-read   shuffle/reader.py, per block fetch
    ipc-decode     shuffle/ipc.py, per frame decode
    mem-pressure   memory/manager.py, per mem_used update (forces spill)
    device-collective  parallel/stage.py DeviceExchange, per shard per
                   collective dispatch (kills the device-resident
                   exchange; the scheduler falls back to file shuffle)
    device-loop    runtime/loop.py, per chunk boundary of the
                   device-resident stage loop (kills the loop mid-fold;
                   the task falls back wholesale to the staged
                   per-batch executor)
    admit          serving/service.py, per admission decision (sheds the
                   query with QueryRejected kind="injected")
    cancel-race    serving/service.py QueryService.cancel, widens the
                   cancel-vs-completion race window
    quota-breach   memory/manager.py, per quota evaluation (forces a
                   per-query quota breach → degradation rung)
    pallas-kernel  kernels/lane.py, per lane-kernel invocation (forces
                   the interpret/scatter fallback path; the engine must
                   degrade, not diverge)
    stream-epoch   streaming/executor.py, at each micro-batch epoch
                   boundary (kills the epoch mid-flight; the stream
                   replays from the last committed checkpoint)
    checkpoint-commit  streaming/checkpoint.py, before the first-wins
                   manifest create (a crash between sink attempt and
                   commit; replay must not double-emit)
    worker-crash   parallel/workers.py, per task dispatch (the child
                   really SIGKILLs itself mid-task; the pool classifies
                   the exit as WorkerCrashed and the retry lands on a
                   different worker)
    worker-hang    parallel/workers.py, per task dispatch (the child
                   suppresses heartbeats and wedges; the pool's liveness
                   deadline detects the miss and kills the process)
    worker-slow    parallel/workers.py, per task dispatch (the child
                   stalls but keeps heartbeating: slow must never be
                   mistaken for dead)
    speculation-loser-commit-race  bridge/tasks.py, when a winning
                   attempt would cancel its speculative sibling
                   (suppresses the cancel so BOTH attempts race the
                   commit; every shuffle tier must reject the late
                   loser)
    replica-crash  fleet/replica.py, per query request (the replica
                   process really SIGKILLs itself mid-query — the host
                   death the router must survive: connection reset →
                   mark the replica down, re-route the query to the
                   next replica in rendezvous order, retry end-to-end)
    replica-hang   fleet/replica.py, per heartbeat (the replica wedges —
                   stops answering pings while its socket stays open;
                   the router's liveness deadline must classify the
                   miss as down and stop routing to it)
    socket-torn-frame  shuffle/ipc.py sock_send_frame, per frame (the
                   sender dies mid-send: the peer sees a length prefix
                   it can never satisfy; readers must classify the tear
                   as retryable FrameTransportClosed loss, never as a
                   ShuffleChecksumError)

Determinism: every decision is a pure function of (seed, site,
occurrence-index) — the k-th evaluation of a site fires or not
regardless of thread interleaving, so a fixed seed gives a fixed fire
*set* even when the task pool races.  Rules either fire on explicit
occurrence indices (`at`) or with probability `p` drawn from a
per-occurrence `random.Random(crc32(seed|site|k))`.

Config (`auron.tpu.faults.*`): `enable` activates the injector from
`rules` + `seed` on first use; tests usually call `install()` /
`scoped()` directly.  Rule-string grammar, comma-separated:

    site=0.25            fire with p=0.25 per occurrence
    site=0.25*3          ... at most 3 times
    site@2+7             fire exactly on occurrences 2 and 7
    site=0.1:corrupt     action `corrupt` (flip a payload byte) instead
                         of raising InjectedFault
"""

from __future__ import annotations

import random
import threading
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, Optional, Tuple

SITES = ("task-start", "shuffle-write", "shuffle-read", "ipc-decode",
         "mem-pressure", "device-collective", "device-loop", "admit",
         "cancel-race", "quota-breach", "pallas-kernel", "stream-epoch",
         "checkpoint-commit", "worker-crash", "worker-hang", "worker-slow",
         "speculation-loser-commit-race", "replica-crash", "replica-hang",
         "socket-torn-frame")

#: dynamically registered sites (register_site): rule validation accepts
#: them alongside the static SITES tuple
_extra_sites: set = set()


def register_site(site: str) -> None:
    """Escape hatch for sites created at runtime (plugins, tests):
    parse_rules validates rule site names against SITES, and a
    dynamically registered site must opt in here or its rules are
    rejected as typos."""
    _extra_sites.add(site)


class InjectedFault(RuntimeError):
    """A scripted transient failure; classified retryable by the task
    pool (the moral equivalent of a lost executor heartbeat)."""


class ShuffleChecksumError(IOError):
    """A shuffle/spill IPC frame failed its CRC32C verification."""


class WorkerCrashed(RuntimeError):
    """A pool worker process died (or missed its liveness deadline) while
    running a task — the lost-executor analog.  Retryable: the task pool
    re-dispatches the attempt, and the crashed worker's id rides along so
    the retry can land on a DIFFERENT worker."""

    def __init__(self, worker_id: Optional[int] = None,
                 exit_code: Optional[int] = None, reason: str = ""):
        self.worker_id = worker_id
        self.exit_code = exit_code
        self.reason = reason
        detail = []
        if worker_id is not None:
            detail.append(f"worker={worker_id}")
        if exit_code is not None:
            detail.append(f"exit={exit_code}")
        if reason:
            detail.append(reason)
        super().__init__("worker crashed"
                         + (f" ({', '.join(detail)})" if detail else ""))


class TaskDeadlineExpired(TimeoutError):
    """The wave deadline passed before (or while) an attempt could run.
    Classified FATAL, not retryable: TimeoutError is an OSError subclass
    and would otherwise look like transient IO, burning maxAttempts
    backoff sleeps an already-expired task can never use."""


class FetchFailedError(RuntimeError):
    """A shuffle block could not be read back intact (Spark's
    FetchFailedException analog).  Carries the lineage the scheduler
    needs to re-run ONLY the poisoned producer map task: the producer
    stage id and map task id that wrote the block."""

    def __init__(self, stage_id: int = -1, map_id: int = -1,
                 reason: str = ""):
        self.stage_id = int(stage_id)
        self.map_id = int(map_id)
        self.reason = reason
        super().__init__(
            f"shuffle fetch failed (stage={stage_id} map={map_id})"
            + (f": {reason}" if reason else ""))


def classify_exception(e: BaseException) -> str:
    """'retryable' | 'fetch-failed' | 'fatal'.

    Retryable = transient IO and injected faults (a fresh attempt can
    succeed); fetch-failed propagates to the DAG scheduler for lineage
    recovery (re-running THIS task would just re-read the same poisoned
    block); everything else — plan/serde/logic errors — is fatal and
    must fail fast without burning retry budget."""
    if isinstance(e, FetchFailedError):
        return "fetch-failed"
    if isinstance(e, (InjectedFault, ShuffleChecksumError, WorkerCrashed,
                      EOFError, ConnectionError, BrokenPipeError,
                      InterruptedError)):
        return "retryable"
    # a worker-side failure arrives re-raised in the parent as a proxy
    # exception carrying the CHILD's classification verdict: honor it
    # (the child saw the real type; the proxy is just a RuntimeError)
    remote = getattr(e, "remote_classify", None)
    if remote in ("retryable", "fetch-failed", "fatal"):
        return remote
    if isinstance(e, (MemoryError, KeyboardInterrupt, SystemExit,
                      TaskDeadlineExpired)):
        return "fatal"
    if isinstance(e, OSError):
        return "retryable"  # transient filesystem/socket trouble
    if type(e).__name__ == "StageLoopFallback":
        # containment escape hatch: every stage-loop caller handles the
        # fallback in place, but if one leaks, the retry runs with the
        # loop declined (bridge/tasks.py) — by name to keep faults.py a
        # leaf module below blaze_tpu.runtime
        return "retryable"
    return "fatal"


@dataclass
class FaultRule:
    site: str
    p: float = 0.0
    at: Tuple[int, ...] = ()       # explicit 1-based occurrence indices
    times: Optional[int] = None    # cap on total fires
    action: str = "raise"          # "raise" | "corrupt"
    fires: int = 0                 # mutated under the injector lock


@dataclass
class _SiteStats:
    evals: int = 0
    fires: int = 0


class FaultInjector:
    """Seeded, counter-deterministic fault decision engine."""

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._rules: Dict[str, list] = {}
        self._counters: Dict[str, int] = {}
        self._stats: Dict[str, _SiteStats] = {}

    def install(self, site: str, p: float = 0.0,
                at: Iterable[int] = (), times: Optional[int] = None,
                action: str = "raise") -> None:
        if action not in ("raise", "corrupt"):
            raise ValueError(f"unknown fault action {action!r}")
        rule = FaultRule(site=site, p=float(p), at=tuple(at),
                         times=times, action=action)
        with self._lock:
            self._rules.setdefault(site, []).append(rule)

    # -- decisions ---------------------------------------------------------
    def decide(self, site: str) -> Optional[FaultRule]:
        """Consume one occurrence of `site`; return the firing rule (or
        None).  Deterministic in the occurrence index, not in which
        thread happened to claim it."""
        with self._lock:
            rules = self._rules.get(site)
            stats = self._stats.setdefault(site, _SiteStats())
            stats.evals += 1
            if not rules:
                return None
            k = self._counters.get(site, 0) + 1
            self._counters[site] = k
            for rule in rules:
                if rule.times is not None and rule.fires >= rule.times:
                    continue
                if rule.at:
                    hit = k in rule.at
                elif rule.p > 0.0:
                    # crc32-keyed seed: stable across processes (str
                    # hash() is salted) and legal Random() input
                    rng = random.Random(
                        zlib.crc32(f"{self.seed}|{site}|{k}".encode()))
                    hit = rng.random() < rule.p
                else:
                    hit = False
                if hit:
                    rule.fires += 1
                    stats.fires += 1
                    return rule
        return None

    def stats(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {s: {"evals": st.evals, "fires": st.fires}
                    for s, st in self._stats.items()}

    def reset_counters(self) -> None:
        with self._lock:
            self._counters.clear()
            self._stats.clear()
            for rules in self._rules.values():
                for r in rules:
                    r.fires = 0


def _check_site(site: str) -> str:
    """A typo'd site name would silently never fire — the worst possible
    chaos-rule failure mode (the soak 'passes' having injected nothing).
    Fail loudly at parse time; register_site() is the escape hatch for
    sites created at runtime."""
    if site not in SITES and site not in _extra_sites:
        raise ValueError(
            f"unknown fault site {site!r}; known sites: "
            f"{', '.join(SITES)}"
            + (f"; registered: {', '.join(sorted(_extra_sites))}"
               if _extra_sites else "")
            + " (faults.register_site() declares dynamic sites)")
    return site


def parse_rules(spec: str) -> list:
    """Parse the `auron.tpu.faults.rules` grammar into (site, kwargs).
    Site names are validated against SITES (+ register_site entries)."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        action = "raise"
        if ":" in part:
            part, action = part.rsplit(":", 1)
        times = None
        if "*" in part:
            part, times_s = part.rsplit("*", 1)
            times = int(times_s)
        if "@" in part:
            site, at_s = part.split("@", 1)
            at = tuple(int(x) for x in at_s.split("+"))
            out.append((_check_site(site.strip()),
                        dict(at=at, times=times, action=action)))
        elif "=" in part:
            site, p_s = part.split("=", 1)
            out.append((_check_site(site.strip()),
                        dict(p=float(p_s), times=times, action=action)))
        else:
            raise ValueError(f"bad fault rule {part!r} "
                             f"(want site=p or site@k)")
    return out


# -- process-wide registry --------------------------------------------------

_lock = threading.Lock()
_injector: Optional[FaultInjector] = None
_conf_probed = False  # lazy one-shot auron.tpu.faults.enable probe


def install(site: str, **kw: Any) -> FaultInjector:
    """Programmatic rule install (tests); activates the injector."""
    global _injector
    with _lock:
        if _injector is None:
            from blaze_tpu import config
            _injector = FaultInjector(seed=config.FAULTS_SEED.get())
        inj = _injector
    inj.install(site, **kw)
    return inj


def configure(rules: str, seed: int = 0) -> FaultInjector:
    """Replace the active injector with one built from a rule string
    (the `bench.py --chaos` entry point)."""
    global _injector, _conf_probed
    inj = FaultInjector(seed=seed)
    for site, kw in parse_rules(rules):
        inj.install(site, **kw)
    with _lock:
        _injector = inj
        _conf_probed = True
    return inj


def activate_from_conf() -> Optional[FaultInjector]:
    """Build the injector from `auron.tpu.faults.*` when enabled."""
    global _injector, _conf_probed
    from blaze_tpu import config
    with _lock:
        _conf_probed = True
        if not config.FAULTS_ENABLE.get():
            _injector = None
            return None
        inj = FaultInjector(seed=config.FAULTS_SEED.get())
        for site, kw in parse_rules(config.FAULTS_RULES.get()):
            inj.install(site, **kw)
        _injector = inj
        return inj


def clear() -> None:
    """Deactivate injection entirely (tests/bench teardown)."""
    global _injector, _conf_probed
    with _lock:
        _injector = None
        _conf_probed = False


def _current() -> Optional[FaultInjector]:
    global _conf_probed
    inj = _injector
    if inj is not None:
        return inj
    if _conf_probed:
        return None
    # first call since clear(): honor a conf-enabled injector.  The
    # probe result is cached — per-frame hot paths must not pay a
    # config lookup when injection is off.
    with _lock:
        if _injector is not None:
            return _injector
        _conf_probed = True
    from blaze_tpu import config
    if config.FAULTS_ENABLE.get():
        return activate_from_conf()
    return None


def _note_fire(site: str) -> None:
    from blaze_tpu.bridge import xla_stats
    xla_stats.note_fault_injected()
    from blaze_tpu.bridge import tracing
    tracing.instant("fault_injected", site=site)


def maybe_fail(site: str, **ctx: Any) -> None:
    """Raise InjectedFault if a raise-action rule fires for `site`."""
    inj = _current()
    if inj is None:
        return
    rule = inj.decide(site)
    if rule is not None and rule.action == "raise":
        _note_fire(site)
        raise InjectedFault(
            f"injected fault at {site}"
            + (f" ({', '.join(f'{k}={v}' for k, v in ctx.items())})"
               if ctx else ""))


def corrupt(site: str, payload: bytes, **ctx: Any) -> bytes:
    """Return `payload`, bit-flipped if a corrupt-action rule fires for
    `site`; a raise-action rule on the same site raises instead."""
    inj = _current()
    if inj is None or not payload:
        return payload
    rule = inj.decide(site)
    if rule is None:
        return payload
    _note_fire(site)
    if rule.action == "raise":
        raise InjectedFault(f"injected fault at {site}")
    buf = bytearray(payload)
    pos = (inj.seed + rule.fires) % len(buf)
    buf[pos] ^= 0xFF
    return bytes(buf)


def fires(site: str, **ctx: Any) -> bool:
    """Non-raising decision (the mem-pressure site: injection forces a
    spill round rather than throwing inside an operator)."""
    inj = _current()
    if inj is None:
        return False
    if inj.decide(site) is None:
        return False
    _note_fire(site)
    return True


def stats() -> Dict[str, Dict[str, int]]:
    inj = _injector
    return inj.stats() if inj is not None else {}


def reset_counters() -> None:
    inj = _injector
    if inj is not None:
        inj.reset_counters()


class scoped:
    """`with faults.scoped(("task-start", dict(at=(1,)))): ...` —
    install rules for a block, restore the previous injector on exit."""

    def __init__(self, *rules: Tuple[str, Dict[str, Any]], seed: int = 0):
        self._rules = rules
        self._seed = seed
        self._saved: Optional[FaultInjector] = None
        self._saved_probed = False

    def __enter__(self) -> FaultInjector:
        global _injector, _conf_probed
        with _lock:
            self._saved, self._saved_probed = _injector, _conf_probed
            inj = FaultInjector(seed=self._seed)
            _injector, _conf_probed = inj, True
        for site, kw in self._rules:
            inj.install(site, **kw)
        return inj

    def __exit__(self, *exc) -> bool:
        global _injector, _conf_probed
        with _lock:
            _injector, _conf_probed = self._saved, self._saved_probed
        return False
